"""Batched score serving over a follower's table versions.

Two layers:

- :class:`Scorer` — the compiled forward-only step. Reuses the
  ``set_test_mode`` eval path of ``train_step.py`` verbatim (forward +
  metrics, no pushes, no dense update) so serving numerics are the
  trainer's eval numerics by construction; tests/test_eval_mode.py pins
  eval-forward == train-forward preds at equal params. Per request it
  builds a tiny PassWorkingSet from the request's keys, pulls rows from
  a pluggable row source (a follower TableVersion, or a trainer's
  HostSparseTable for the parity gate), packs with the standard
  device packer, and runs one jitted step. Shapes are bucketed on three
  axes — records pad to the configured batch size, working-set capacity
  rounds to ``serve_row_bucket``, flat keys to ``serve_key_bucket`` — so
  XLA compiles a small bounded program family instead of one program per
  request size (the Ragged-Paged-Attention lesson: inference wants its
  own latency-shaped execution path, not ad-hoc shapes).

- :class:`ScoreServer` — an in-process batching front-end: requests
  queue up, a single batcher thread coalesces them (up to the batch
  size, waiting at most ``serve_batch_wait_ms``), scores them against
  the follower's CURRENT version, and resolves per-request futures.
  Train-to-serve staleness is stamped here: the first request answered
  from a version records ``now - published_unix``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

try:
    import jax
    import jax.numpy as jnp
    import optax
# optional-dependency gate: serving falls back to numpy apply paths
# pbox-lint: disable=EXC007
except Exception:  # pragma: no cover
    jax = jnp = optax = None

from paddlebox_tpu import config
from paddlebox_tpu.data.device_pack import pack_batch
from paddlebox_tpu.data.slot_record import build_batch
from paddlebox_tpu.metrics.auc import auc_init
from paddlebox_tpu.serve.scoring_table import TableVersion
from paddlebox_tpu.table.sparse_table import PassWorkingSet
from paddlebox_tpu.train.train_step import TrainState, make_train_step
from paddlebox_tpu.obs.histogram import Histogram
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE, STAT_SET


class ServeOverloadError(RuntimeError):
    """Typed load-shed refusal: the batcher queue is past
    ``serve_shed_queue_depth``. Clients treat it as retriable — on another
    follower, not by growing this one's backlog."""


class ServeTimeoutError(TimeoutError):
    """Typed per-request deadline expiry: the batcher did not answer
    within the caller's budget (``serve_request_timeout_ms`` by default).
    Subclasses TimeoutError so pre-fleet callers that caught the builtin
    keep working."""


class _RowSource:
    """Adapter giving PassWorkingSet.finalize a host-table interface over
    any pull function (TableVersion lookup, or a live HostSparseTable)."""

    def __init__(self, layout, pull_fn):
        self.layout = layout
        self._pull = pull_fn

    def pull_or_create(self, keys: np.ndarray) -> np.ndarray:
        return self._pull(keys)


def version_source(layout, version: TableVersion) -> _RowSource:
    """Row source over an immutable served version; misses (keys the
    published model has never seen) pull the zero row and are counted.

    Versions carrying a device tier pull through the miss-fallback ladder
    (mesh-sharded hot rows first, host rows on tier miss) — bitwise-equal
    rows either way, so the compiled scorer never knows which path fed it.
    """

    if version.device_tier is not None:
        def pull(keys: np.ndarray) -> np.ndarray:
            rows, _, n_miss = version.lookup_rows_tiered(keys)
            if n_miss:
                STAT_ADD("serve.miss_keys", n_miss)
            return rows
    else:
        def pull(keys: np.ndarray) -> np.ndarray:
            rows, n_miss = version.lookup_rows(keys)
            if n_miss:
                STAT_ADD("serve.miss_keys", n_miss)
            return rows

    return _RowSource(layout, pull)


def table_source(layout, table) -> _RowSource:
    """Row source over a live HostSparseTable (the trainer-direct side of
    the bitwise-parity gate). Callers score keys the table holds; a
    missing key would be created by pull_or_create, so parity probes use
    keys drawn from trained data."""
    return _RowSource(layout, table.pull_or_create)


class Scorer:
    """Compiled forward-only scoring (one jit cache shared by all callers).

    Stateless across requests apart from the jit cache: params/opt_state
    and the row source are per-call, so one Scorer can serve follower
    versions and trainer-direct parity probes with the SAME compiled
    program — which is exactly what makes the bitwise gate meaningful.
    Thread-safe: concurrent score_records calls build independent working
    sets and feed the same jitted function.
    """

    def __init__(self, model, cfg, dense_opt=None, dense_slot=None, dense_dim: int = 0):
        self.cfg = cfg
        self.dense_slot = dense_slot
        self.dense_dim = dense_dim
        # NO donation (unlike the training jit): params are reused across
        # requests, donating them would delete the live buffers
        self._step = jax.jit(
            make_train_step(
                model.apply, dense_opt or optax.adam(1e-3), cfg, eval_mode=True
            )
        )

    def score_records(
        self, records: Sequence, schema, source: _RowSource, params, opt_state=None
    ) -> np.ndarray:
        """preds float32 [len(records)] — deterministic in (rows, params)."""
        if params is None:
            raise RuntimeError(
                "no dense params to score with — the follower has not "
                "loaded a published dense file yet"
            )
        n, B = len(records), self.cfg.batch_size
        out = np.empty(n, dtype=np.float32)
        for lo in range(0, n, B):
            chunk = list(records[lo : lo + B])
            out[lo : lo + len(chunk)] = self._score_chunk(
                chunk, schema, source, params, opt_state
            )
        return out

    def _score_chunk(self, records, schema, source, params, opt_state) -> np.ndarray:
        m = len(records)
        # pad to the compiled batch size by repeating the tail record:
        # per-example forward math never mixes examples, so preds[:m] are
        # bit-identical whatever rides in the ghost rows
        padded = records + [records[-1]] * (self.cfg.batch_size - m)
        batch = build_batch(padded, schema)
        ws = PassWorkingSet(n_mesh_shards=1)
        ws.add_keys(batch.keys)
        dev = ws.finalize(source, round_to=config.get_flag("serve_row_bucket"))
        db = pack_batch(
            batch,
            ws,
            schema,
            dense_slot=self.dense_slot,
            dense_dim=self.dense_dim,
            bucket=config.get_flag("serve_key_bucket"),
        )
        state = TrainState(
            table=jnp.asarray(dev.reshape(-1, source.layout.width)),
            params=params,
            opt_state=opt_state,
            auc=auc_init(self.cfg.auc_buckets),
            step=jnp.zeros((), jnp.int32),
        )
        feed = {k: jnp.asarray(v) for k, v in db.as_dict().items()}
        _, metrics = self._step(state, feed)
        return np.asarray(metrics["preds"], dtype=np.float32)[:m]


class _Pending:
    """One submitted request: records in, preds (or an error) out."""

    __slots__ = ("records", "t_submit", "done", "preds", "error", "delta_idx")

    def __init__(self, records):
        self.records = records
        self.t_submit = time.perf_counter()
        self.done = threading.Event()
        self.preds: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.delta_idx: int = -1

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            STAT_ADD("serve.request_timeouts")
            raise ServeTimeoutError(
                f"score request timed out after {timeout}s — the batcher "
                "never answered (wedged scorer or overloaded queue)"
            )
        if self.error is not None:
            raise self.error
        return self.preds


class ScoreServer:
    """In-process batched scoring front-end over a Follower.

    One batcher thread owns all scoring; submitters only enqueue and wait
    on their request's event. Latency samples and served-version history
    are kept for the soak report (lists grow one entry per request /
    version — bounded by the run, not the process lifetime).
    """

    def __init__(self, follower, scorer: Scorer, schema):
        self.follower = follower
        self.scorer = scorer
        self.schema = schema
        self._q: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # per-server latency distribution (the soak report's source of
        # truth) — mirrored into the global registry via STAT_OBSERVE so
        # obs_report sees serve latency next to every other series
        self.latency_hist = Histogram()  # thread-safe itself
        self.served_indices: List[int] = []  # guarded-by: _lock
        self.staleness: List[Tuple[int, float]] = []  # guarded-by: _lock

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._batcher, name="score-batcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)

    # ---- request surface -------------------------------------------------

    def submit(self, records: Sequence) -> _Pending:
        if not len(records):
            raise ValueError("empty score request")
        depth = int(config.get_flag("serve_shed_queue_depth"))
        if depth > 0 and self._q.qsize() >= depth:
            # shed at admission, not mid-queue: a refused request costs the
            # client one retry on another follower; an admitted-then-late
            # one costs its full deadline
            STAT_ADD("serve.shed_requests")
            raise ServeOverloadError(
                f"score queue holds >= {depth} requests "
                "(serve_shed_queue_depth) — request shed"
            )
        req = _Pending(list(records))
        self._q.put(req)
        return req

    def score(
        self, records: Sequence, timeout: Optional[float] = None
    ) -> np.ndarray:
        """Synchronous convenience wrapper: submit + wait. ``timeout=None``
        means the ``serve_request_timeout_ms`` flag — a deadline always
        applies, so a wedged batcher surfaces as ServeTimeoutError instead
        of blocking the caller forever."""
        if timeout is None:
            timeout = float(config.get_flag("serve_request_timeout_ms")) / 1000.0
        return self.submit(records).result(timeout)

    def queue_depth(self) -> int:
        """Requests waiting for the batcher (the health-gossip load signal
        and the shed threshold's input)."""
        return self._q.qsize()

    # ---- batcher ---------------------------------------------------------

    def _batcher(self) -> None:
        wait_s = float(config.get_flag("serve_batch_wait_ms")) / 1000.0
        B = self.scorer.cfg.batch_size
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            reqs = [first]
            total = len(first.records)
            deadline = time.perf_counter() + wait_s
            while total < B:
                left = deadline - time.perf_counter()
                if left <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=left)
                except queue.Empty:
                    break
                reqs.append(nxt)
                total += len(nxt.records)
            self._serve_batch(reqs)

    def _serve_batch(self, reqs: List[_Pending]) -> None:
        # one consistent (version, params) pair for the whole batch: the
        # version carries its own dense params, committed under the same
        # atomic swap as the sparse rows
        v = self.follower.version()
        params, opt_state = v.params, v.opt_state
        records = [r for req in reqs for r in req.records]
        try:
            preds = self.scorer.score_records(
                records,
                self.schema,
                version_source(self.follower.layout, v),
                params,
                opt_state,
            )
        except BaseException as e:  # noqa: BLE001 — fault must reach submitters
            for req in reqs:
                req.error = e
                req.done.set()
            STAT_ADD("serve.request_errors", len(reqs))
            return
        now_unix = time.time()
        if v.first_served_unix is None and v.published_unix is not None:
            # train-to-serve staleness: delta publish -> first answer from it
            v.first_served_unix = now_unix
            lag = now_unix - v.published_unix
            STAT_SET("serve.staleness_s", lag)
            with self._lock:
                self.staleness.append((v.delta_idx, lag))
        t_done = time.perf_counter()
        lo = 0
        with self._lock:
            for req in reqs:
                req.preds = preds[lo : lo + len(req.records)]
                req.delta_idx = v.delta_idx
                lo += len(req.records)
                lat_ms = (t_done - req.t_submit) * 1000.0
                self.latency_hist.observe(lat_ms)
                STAT_OBSERVE("serve.latency_ms", lat_ms)
                # the SLO-facing per-request series: obs_report verdicts
                # key on serve.request_ms (one sample per request, both
                # the in-process and fleet-follower paths land here)
                STAT_OBSERVE("serve.request_ms", lat_ms)
                self.served_indices.append(v.delta_idx)
        for req in reqs:
            req.done.set()
        STAT_ADD("serve.requests", len(reqs))
        STAT_ADD("serve.records", len(records))
        STAT_ADD("serve.batches")
        STAT_SET("serve.served_delta_idx", v.delta_idx)

    # ---- reporting -------------------------------------------------------

    def latency_percentiles(self) -> dict:
        """Same report keys as the pre-histogram implementation (the soak
        JSON golden-diff depends on them): n, p50_ms, p99_ms, max_ms."""
        h = self.latency_hist
        n = h.count
        if n == 0:
            return {"n": 0}
        p50, p99 = h.quantiles((0.5, 0.99))
        return {
            "n": n,
            "p50_ms": float(p50),
            "p99_ms": float(p99),
            "max_ms": float(h.max),
        }
