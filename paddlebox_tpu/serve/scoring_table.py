"""Atomic-swap scoring table: the follower's serve-side model state.

The xbox/abacus serving fleet consumes the trainer's per-pass SaveDelta
stream and must never answer a request from a half-applied delta
(box_wrapper.cc publishes whole passes; the serving side swaps whole
models). This module gives the follower that all-or-nothing boundary:

- :class:`TableVersion` — one immutable published state (base + deltas
  1..delta_idx): sorted keys, a :class:`ReplicaCache` holding the rows,
  and the publish metadata (decay epoch, watermark timestamp) that the
  staleness metric is computed from.
- :class:`ScoringTable` — holds the currently served version behind a
  lock. :meth:`commit` builds the NEXT version completely off to the
  side and installs it with a single reference swap; scorers that
  grabbed the old version mid-request keep a complete consistent table.

The kill-mid-apply contract lives here: fault site ``serve.apply_delta``
fires after the next version is fully built but before the swap, so an
injected crash models a follower dying mid-apply — the served version
must remain the previous one, bit-for-bit (tests/test_serve.py pins it).

PR 19 adds the mesh-sharded hot tier (the PullSparseGPU analog for
serving): :class:`DeviceScoringTier` holds exact fp32 copies of the
version's hottest rows (decayed-show >= ``device_tier_hot_show``, the
same ``shows_peek`` signal the adaptive ICI wire uses), sharded over the
mesh with ``NamedSharding`` so each chip owns 1/N of them; lookups route
through the sharded-pull collective with ``serve_key_bucket``-bucketed
request shapes, and only tier misses fall back to the host
:meth:`TableVersion.lookup_rows`. The tier is built inside
:meth:`ScoringTable.commit` (fault site ``serve.tier_build`` sits at the
start of that build) and rides the version object itself, so tier and
host rows install under the SAME single reference swap — a crash
mid-tier-build can never surface a partial tier.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.table.replica_cache import ReplicaCache
from paddlebox_tpu.table.sparse_table import key_to_shard
from paddlebox_tpu.utils.faultinject import fire as _fault_fire
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_SET

try:
    import jax
# optional-dependency gate: without jax the tier degrades to host-only
# pbox-lint: disable=EXC007
except Exception:  # pragma: no cover
    jax = None


class DeviceScoringTier:
    """Device-resident hot-row tier of one TableVersion. Immutable after
    build (same contract as the version itself): per-shard sorted key
    arrays stay on the host for routing, the row blocks live on device
    sharded over the mesh axis, and lookups run the sharded-pull
    collective with shape-bucketed requests.
    """

    def __init__(self, plan, keys: np.ndarray, rows: np.ndarray):
        from paddlebox_tpu.data.device_pack import _round_bucket
        from paddlebox_tpu.parallel.mesh import put_sharded

        self.plan = plan
        self.n_shards = plan.n_devices
        self.width = int(rows.shape[1])
        keys = np.asarray(keys, dtype=np.uint64)
        owner = key_to_shard(keys, self.n_shards)
        counts = np.bincount(owner, minlength=self.n_shards)
        # +1 reserves a guaranteed zero padding row per shard; rounding to
        # serve_row_bucket bounds the distinct table shapes across commits
        cap = _round_bucket(
            int(counts.max()) + 1 if len(keys) else 1,
            int(config.get_flag("serve_row_bucket")),
        )
        block = np.zeros((self.n_shards, cap, self.width), dtype=np.float32)
        self._shard_keys: List[np.ndarray] = []
        for s in range(self.n_shards):
            sel = np.nonzero(owner == s)[0]
            sk = keys[sel]
            order = np.argsort(sk)
            self._shard_keys.append(sk[order])
            block[s, : len(sk)] = rows[sel][order]
        self.pad_rank = cap - 1
        self.table = put_sharded(plan, block)  # [n_shards, cap, width] on dp
        self.n_rows = int(len(keys))
        self._pull_cache: dict = {}  # K -> compiled collective, guarded-by GIL
        # per-tier hit/miss tallies for the health gossip (the STAT_ADD
        # counters are process-global; gossip wants per-rank numbers)
        self._stat_lock = threading.Lock()
        self.hits = 0  # guarded-by: _stat_lock
        self.misses = 0  # guarded-by: _stat_lock

    def mem_used_mb(self) -> float:
        cap = self.pad_rank + 1
        return self.n_shards * cap * self.width * 4 / 1024.0 / 1024.0

    def _pull_fn(self, K: int):
        fn = self._pull_cache.get(K)
        if fn is None:
            from jax.sharding import PartitionSpec as P

            from paddlebox_tpu.parallel.mesh import shard_map
            from paddlebox_tpu.parallel.sharded_pullpush import (
                sharded_serve_pull,
            )

            plan = self.plan
            axis = plan.axis

            def body(table_block, req_block):
                # per device: table_block [1, cap, W], req_block [1, n, K]
                return sharded_serve_pull(
                    table_block[0], req_block[0], axis_name=axis
                )[None]

            fn = jax.jit(
                shard_map(
                    body,
                    plan.mesh,
                    in_specs=(P(axis), P(axis)),
                    out_specs=P(axis),
                )
            )
            self._pull_cache[K] = fn
        return fn

    def lookup_rows(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Tier rows for uint64 ``keys``: (rows [n, width], hit bool [n]).

        Hit rows are bitwise the committed version's rows (the tier stores
        exact fp32 copies and the pull is a pure routed gather); miss rows
        are zero and the caller falls back to the host version.
        """
        from paddlebox_tpu.data.device_pack import route_serve_requests

        q = np.asarray(keys, dtype=np.uint64)
        m = len(q)
        out = np.zeros((m, self.width), dtype=np.float32)
        hit = np.zeros(m, dtype=bool)
        local = np.zeros(m, dtype=np.int64)
        if m and self.n_rows:
            owner = key_to_shard(q, self.n_shards)
            for s in range(self.n_shards):
                sel = np.nonzero(owner == s)[0]
                sk = self._shard_keys[s]
                if len(sel) == 0 or len(sk) == 0:
                    continue
                pos = np.searchsorted(sk, q[sel])
                pos = np.minimum(pos, len(sk) - 1)
                h = sk[pos] == q[sel]
                hit[sel] = h
                local[sel] = pos
            idx = np.nonzero(hit)[0]
            if len(idx):
                req, pos, K = route_serve_requests(
                    owner[idx],
                    local[idx],
                    self.n_shards,
                    int(config.get_flag("serve_key_bucket")),
                    self.pad_rank,
                )
                pulled = np.asarray(self._pull_fn(K)(self.table, req))
                out[idx] = pulled.reshape(-1, self.width)[pos]
        n_hit = int(np.count_nonzero(hit))
        with self._stat_lock:
            self.hits += n_hit
            self.misses += m - n_hit
        return out, hit


class TableVersion:
    """One immutable served state. Never mutated after construction —
    that immutability is what makes the ScoringTable swap atomic."""

    __slots__ = (
        "date",
        "delta_idx",
        "decay_epoch",
        "published_unix",
        "keys",
        "cache",
        "rows",
        "params",
        "opt_state",
        "device_tier",
        "first_served_unix",
    )

    def __init__(
        self,
        date: Optional[str],
        delta_idx: int,
        decay_epoch: int,
        published_unix: Optional[float],
        keys: np.ndarray,
        cache: ReplicaCache,
        params=None,
        opt_state=None,
        device_tier: Optional[DeviceScoringTier] = None,
    ):
        self.date = date
        self.delta_idx = delta_idx
        self.decay_epoch = decay_epoch
        self.published_unix = published_unix
        self.keys = keys  # uint64 [n], sorted
        self.cache = cache
        # the dense params this sparse state pairs with (the cursor pairs
        # them on the producer side; carrying them IN the version keeps the
        # pair atomic under the same swap — a crash between dense load and
        # commit can never serve new dense over old sparse)
        self.params = params
        self.opt_state = opt_state
        # the mesh-sharded hot tier (None = host-only serving); built by
        # commit() so it installs under the same atomic swap as the rows
        self.device_tier = device_tier
        # materialized once (versions are immutable) so lookups are a
        # searchsorted + fancy-index, not a per-request stack
        self.rows = cache.host_array()  # f32 [n, width]
        # stamped by the server the first time a request is answered from
        # this version; (first_served - published) IS the train-to-serve
        # staleness the soak reports. Single batcher thread writes it.
        self.first_served_unix: Optional[float] = None

    @property
    def n_rows(self) -> int:
        return len(self.keys)

    def lookup_rows(self, keys: np.ndarray) -> Tuple[np.ndarray, int]:
        """Rows for uint64 ``keys``; returns (rows [n, width], miss count).

        Missing keys get the zero row: a key the published model has never
        seen scores from a cold embedding, exactly like a fresh-created
        (pre-first-push) trainer row with zero counters would after the
        show/clk CVM transform zeroes out.
        """
        q = np.asarray(keys, dtype=np.uint64)
        out = np.zeros((len(q), self.cache.dim), dtype=np.float32)
        n_miss = len(q)
        if len(self.keys) and len(q):
            pos = np.searchsorted(self.keys, q)
            pos = np.minimum(pos, len(self.keys) - 1)
            hit = self.keys[pos] == q
            out[hit] = self.rows[pos[hit]]
            n_miss = int(np.count_nonzero(~hit))
        if n_miss:
            # the zero-row fallback is intentional but must never be
            # silent: an all-miss request usually means a key-hash or
            # lineage bug, and only the counter makes that visible
            STAT_ADD("serve.key_misses", n_miss)
        return out, n_miss

    def lookup_rows_tiered(
        self, keys: np.ndarray
    ) -> Tuple[np.ndarray, int, int]:
        """The serve-side miss-fallback ladder: device tier first, host
        rows for tier misses. Returns (rows [n, width], tier misses, key
        misses) — bitwise-equal rows to :meth:`lookup_rows` always,
        because the tier stores exact copies of the same rows.

        Counter split: ``serve.device_tier_misses`` counts keys the hot
        tier did not hold (answered from the host path), while the
        existing ``serve.key_misses`` keeps counting keys the published
        model has never seen at all (zero-row fallback) — tier misses
        are a capacity/hotness signal, key misses a lineage signal.
        """
        if self.device_tier is None:
            rows, n_key_miss = self.lookup_rows(keys)
            return rows, 0, n_key_miss
        q = np.asarray(keys, dtype=np.uint64)
        rows, hit = self.device_tier.lookup_rows(q)
        n_hit = int(np.count_nonzero(hit))
        n_tier_miss = len(q) - n_hit
        if n_hit:
            STAT_ADD("serve.device_tier_hits", n_hit)
        n_key_miss = 0
        if n_tier_miss:
            STAT_ADD("serve.device_tier_misses", n_tier_miss)
            cold = ~hit
            rows[cold], n_key_miss = self.lookup_rows(q[cold])
        return rows, n_tier_miss, n_key_miss


def _empty_version(width: int) -> TableVersion:
    return TableVersion(
        date=None,
        delta_idx=-1,
        decay_epoch=0,
        published_unix=None,
        keys=np.zeros(0, dtype=np.uint64),
        cache=ReplicaCache(width),
    )


# one mesh plan per process for serve tiers: versions come and go every
# commit, the device topology does not. None after a failed probe = no
# mesh available, the tier degrades to host-only for the process lifetime.
_tier_plan = None
_tier_plan_probed = False
_tier_plan_lock = threading.Lock()


def _serve_mesh_plan():
    global _tier_plan, _tier_plan_probed
    with _tier_plan_lock:
        if not _tier_plan_probed:
            _tier_plan_probed = True
            try:
                if jax is None:
                    raise RuntimeError("jax unavailable")
                from paddlebox_tpu.parallel.mesh import make_mesh

                _tier_plan = make_mesh()
            # degrade-clean gate: any backend/mesh failure means host-only
            # serving, never a serving outage
            # pbox-lint: disable=EXC007
            except Exception:
                _tier_plan = None
                STAT_ADD("serve.device_tier_unavailable")
        return _tier_plan


def build_device_tier(
    keys: np.ndarray, rows: np.ndarray, hotness: np.ndarray
) -> Optional[DeviceScoringTier]:
    """Select the hot rows and place them on the mesh; None when no mesh
    is available (host-only degrade). Runs inside the commit() build
    window — the ``serve.tier_build`` fault site fires at the start, so a
    mid-build crash aborts the whole commit before anything is visible.
    """
    plan = _serve_mesh_plan()
    if plan is None:
        return None
    _fault_fire("serve.tier_build")  # window: tier building, nothing visible
    hotness = np.asarray(hotness, dtype=np.float32)
    idx = np.nonzero(hotness >= float(config.get_flag("device_tier_hot_show")))[0]
    cap = int(config.get_flag("device_tier_capacity"))
    if len(idx) > cap:
        # hottest rows win; sort keeps the selection deterministic under
        # show ties so a healed retry rebuilds the identical tier
        keep = np.argsort(-hotness[idx], kind="stable")[:cap]
        idx = np.sort(idx[keep])
    tier = DeviceScoringTier(plan, keys[idx], rows[idx])
    STAT_SET("serve.device_tier_rows", tier.n_rows)
    STAT_SET("serve.device_tier_mem_mb", tier.mem_used_mb())
    STAT_ADD("serve.device_tier_builds")
    return tier


class ScoringTable:
    """The follower's served table: an atomically swappable TableVersion.

    Readers call :meth:`version` once per request and use that object for
    the whole request; writers call :meth:`commit` with the complete next
    state. There is no in-place mutation path on purpose.
    """

    def __init__(self, width: int):
        self.width = width
        self._lock = threading.Lock()
        self._version: TableVersion = _empty_version(width)  # guarded-by: _lock
        self._history: List[int] = []  # guarded-by: _lock  (committed delta idxs)

    def version(self) -> TableVersion:
        with self._lock:
            return self._version

    def committed_indices(self) -> List[int]:
        """Delta indices in commit order (monotonicity probe for tests)."""
        with self._lock:
            return list(self._history)

    def commit(
        self,
        keys: np.ndarray,
        rows: np.ndarray,
        *,
        date: str,
        delta_idx: int,
        decay_epoch: int,
        published_unix: Optional[float] = None,
        params=None,
        opt_state=None,
        hotness: Optional[np.ndarray] = None,
    ) -> TableVersion:
        """Build and install the next version, all-or-nothing.

        ``keys`` must be sorted uint64 with ``rows`` aligned ([n, width]).
        ``hotness`` (decayed shows aligned with ``keys``, the follower's
        ``shows_peek``) opts this version into the device scoring tier;
        None keeps the host-only path bitwise (the ablation default).
        Everything expensive (cache build, row materialization, the device
        tier) happens BEFORE the swap; the swap itself is one reference
        assignment under the lock. A crash anywhere before it (the
        ``serve.tier_build`` and ``serve.apply_delta`` fault sites sit in
        that window) leaves the previous version served.
        """
        cache = ReplicaCache(self.width)
        if len(rows):
            cache.add_batch(rows)
        tier = None
        if hotness is not None and len(keys):
            tier = build_device_tier(
                np.asarray(keys, dtype=np.uint64),
                np.asarray(rows, dtype=np.float32),
                hotness,
            )
        nxt = TableVersion(
            date=date,
            delta_idx=delta_idx,
            decay_epoch=decay_epoch,
            published_unix=published_unix,
            keys=np.asarray(keys, dtype=np.uint64),
            cache=cache,
            params=params,
            opt_state=opt_state,
            device_tier=tier,
        )
        _fault_fire("serve.apply_delta")  # window: built, not yet visible
        with self._lock:
            self._version = nxt
            self._history.append(delta_idx)
        cache.publish_serve_stats()
        STAT_SET("serve.version_delta_idx", delta_idx)
        STAT_ADD("serve.version_commits")
        return nxt
