"""Atomic-swap scoring table: the follower's serve-side model state.

The xbox/abacus serving fleet consumes the trainer's per-pass SaveDelta
stream and must never answer a request from a half-applied delta
(box_wrapper.cc publishes whole passes; the serving side swaps whole
models). This module gives the follower that all-or-nothing boundary:

- :class:`TableVersion` — one immutable published state (base + deltas
  1..delta_idx): sorted keys, a :class:`ReplicaCache` holding the rows,
  and the publish metadata (decay epoch, watermark timestamp) that the
  staleness metric is computed from.
- :class:`ScoringTable` — holds the currently served version behind a
  lock. :meth:`commit` builds the NEXT version completely off to the
  side and installs it with a single reference swap; scorers that
  grabbed the old version mid-request keep a complete consistent table.

The kill-mid-apply contract lives here: fault site ``serve.apply_delta``
fires after the next version is fully built but before the swap, so an
injected crash models a follower dying mid-apply — the served version
must remain the previous one, bit-for-bit (tests/test_serve.py pins it).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

from paddlebox_tpu.table.replica_cache import ReplicaCache
from paddlebox_tpu.utils.faultinject import fire as _fault_fire
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_SET


class TableVersion:
    """One immutable served state. Never mutated after construction —
    that immutability is what makes the ScoringTable swap atomic."""

    __slots__ = (
        "date",
        "delta_idx",
        "decay_epoch",
        "published_unix",
        "keys",
        "cache",
        "rows",
        "params",
        "opt_state",
        "first_served_unix",
    )

    def __init__(
        self,
        date: Optional[str],
        delta_idx: int,
        decay_epoch: int,
        published_unix: Optional[float],
        keys: np.ndarray,
        cache: ReplicaCache,
        params=None,
        opt_state=None,
    ):
        self.date = date
        self.delta_idx = delta_idx
        self.decay_epoch = decay_epoch
        self.published_unix = published_unix
        self.keys = keys  # uint64 [n], sorted
        self.cache = cache
        # the dense params this sparse state pairs with (the cursor pairs
        # them on the producer side; carrying them IN the version keeps the
        # pair atomic under the same swap — a crash between dense load and
        # commit can never serve new dense over old sparse)
        self.params = params
        self.opt_state = opt_state
        # materialized once (versions are immutable) so lookups are a
        # searchsorted + fancy-index, not a per-request stack
        self.rows = cache.host_array()  # f32 [n, width]
        # stamped by the server the first time a request is answered from
        # this version; (first_served - published) IS the train-to-serve
        # staleness the soak reports. Single batcher thread writes it.
        self.first_served_unix: Optional[float] = None

    @property
    def n_rows(self) -> int:
        return len(self.keys)

    def lookup_rows(self, keys: np.ndarray) -> Tuple[np.ndarray, int]:
        """Rows for uint64 ``keys``; returns (rows [n, width], miss count).

        Missing keys get the zero row: a key the published model has never
        seen scores from a cold embedding, exactly like a fresh-created
        (pre-first-push) trainer row with zero counters would after the
        show/clk CVM transform zeroes out.
        """
        q = np.asarray(keys, dtype=np.uint64)
        out = np.zeros((len(q), self.cache.dim), dtype=np.float32)
        n_miss = len(q)
        if len(self.keys) and len(q):
            pos = np.searchsorted(self.keys, q)
            pos = np.minimum(pos, len(self.keys) - 1)
            hit = self.keys[pos] == q
            out[hit] = self.rows[pos[hit]]
            n_miss = int(np.count_nonzero(~hit))
        if n_miss:
            # the zero-row fallback is intentional but must never be
            # silent: an all-miss request usually means a key-hash or
            # lineage bug, and only the counter makes that visible
            STAT_ADD("serve.key_misses", n_miss)
        return out, n_miss


def _empty_version(width: int) -> TableVersion:
    return TableVersion(
        date=None,
        delta_idx=-1,
        decay_epoch=0,
        published_unix=None,
        keys=np.zeros(0, dtype=np.uint64),
        cache=ReplicaCache(width),
    )


class ScoringTable:
    """The follower's served table: an atomically swappable TableVersion.

    Readers call :meth:`version` once per request and use that object for
    the whole request; writers call :meth:`commit` with the complete next
    state. There is no in-place mutation path on purpose.
    """

    def __init__(self, width: int):
        self.width = width
        self._lock = threading.Lock()
        self._version: TableVersion = _empty_version(width)  # guarded-by: _lock
        self._history: List[int] = []  # guarded-by: _lock  (committed delta idxs)

    def version(self) -> TableVersion:
        with self._lock:
            return self._version

    def committed_indices(self) -> List[int]:
        """Delta indices in commit order (monotonicity probe for tests)."""
        with self._lock:
            return list(self._history)

    def commit(
        self,
        keys: np.ndarray,
        rows: np.ndarray,
        *,
        date: str,
        delta_idx: int,
        decay_epoch: int,
        published_unix: Optional[float] = None,
        params=None,
        opt_state=None,
    ) -> TableVersion:
        """Build and install the next version, all-or-nothing.

        ``keys`` must be sorted uint64 with ``rows`` aligned ([n, width]).
        Everything expensive (cache build, row materialization) happens
        BEFORE the swap; the swap itself is one reference assignment under
        the lock. A crash anywhere before it (the ``serve.apply_delta``
        fault site sits in that window) leaves the previous version served.
        """
        cache = ReplicaCache(self.width)
        if len(rows):
            cache.add_batch(rows)
        nxt = TableVersion(
            date=date,
            delta_idx=delta_idx,
            decay_epoch=decay_epoch,
            published_unix=published_unix,
            keys=np.asarray(keys, dtype=np.uint64),
            cache=cache,
            params=params,
            opt_state=opt_state,
        )
        _fault_fire("serve.apply_delta")  # window: built, not yet visible
        with self._lock:
            self._version = nxt
            self._history.append(delta_idx)
        cache.publish_serve_stats()
        STAT_SET("serve.version_delta_idx", delta_idx)
        STAT_ADD("serve.version_commits")
        return nxt
