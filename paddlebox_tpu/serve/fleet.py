"""Serving fleet: networked front-end, shared staging, health/drain gossip.

The fleet-scale half of the serving plane (ROADMAP item 1). Three pieces,
all riding the existing PBTX v3 framed transport — CRC'd frames, seq
numbers + replay-on-reconnect, heartbeats, and the wire codec come for
free; there is deliberately NO new RPC layer:

- :class:`FleetStage` — one stager per host mirrors the published
  base+delta chain from the origin checkpoint root into a host-local
  ``fleet_stage_dir`` exactly once per watermark advance. N followers on
  the host tail the STAGE, so the origin is fetched once per publish, not
  N times. The stage watermark is written (atomically) only after every
  link is mirrored and CRC-verified, so a torn stage fetch can never
  surface a partial version (fault site ``serve.fleet_stage``).

- :class:`FleetFollower` — wraps a :class:`Follower` + :class:`ScoreServer`
  behind a transport rank: a request loop answers ``serve:req`` frames
  with ``serve:resp`` frames, a gossip loop beats ``ctl:serve:health``
  (state, chain position, staleness, queue depth) to the front-end, and a
  ``ctl:serve:drain`` command flips the explicit drain protocol: finish
  in-flight, refuse new (typed refusal on the wire), announce via gossip.

- :class:`FleetClient` — the load-balancing front-end client: routes each
  request to a queryable follower (per-follower health view: a lagging,
  mid-epoch-re-anchor, draining, or silent follower is marked and not
  queried), enforces per-request deadlines, retries with bounded
  exponential backoff on a DIFFERENT follower, and hedges: when the
  primary has not answered within ``serve_hedge_ms`` the same request is
  re-sent to a second follower and the first answer wins (responses carry
  the request id, so the loser is simply a counted duplicate).

Degradation story: load-shedding lives in ScoreServer.submit (typed
:class:`ServeOverloadError` past ``serve_shed_queue_depth``); a corrupt or
torn publish never removes a follower from rotation — the follower keeps
serving its last good version (PR 7 skip semantics) and the fleet view
sees at most a "lagging" mark until the chain heals. docs/SERVING.md has
the follower-health state machine.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import shutil
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.data.parser import parse_line
from paddlebox_tpu.obs.histogram import Histogram
from paddlebox_tpu.serve.follower import Follower, verify_chain_link
from paddlebox_tpu.serve.server import (
    ScoreServer,
    Scorer,
    ServeOverloadError,
    ServeTimeoutError,
)
from paddlebox_tpu.train.checkpoint import (
    _file_crc32,
    read_watermark,
    validate_watermark,
)
from paddlebox_tpu.utils.faultinject import fire
from paddlebox_tpu.utils.fs import atomic_write
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE, STAT_SET

logger = logging.getLogger(__name__)

# PBTX tags of the serve plane. serve:req / serve:resp are the front-end
# framing (data plane); ctl:serve:* is control gossip. All four are part
# of the extracted protocol vocabulary (analysis/protocol.py lists
# "serve:" in CONTROL_PREFIXES), so DST009 statically proves every send
# here has a matching recv and tests/test_protocol_pin.py pins the live
# tags against the extraction.
_REQ_TAG = "serve:req"
_RESP_TAG = "serve:resp"
_HEALTH_TAG = "ctl:serve:health"
_DRAIN_TAG = "ctl:serve:drain"

# response frame: id, status, delta_idx, n — then n float32 preds (OK)
# or a utf-8 detail message (any refusal/error status)
_RESP = struct.Struct("<QBiI")
_ST_OK = 0
_ST_OVERLOAD = 1
_ST_DRAINING = 2
_ST_ERROR = 3
_ST_TIMEOUT = 4
_ST_NAMES = {
    _ST_OK: "ok",
    _ST_OVERLOAD: "overload",
    _ST_DRAINING: "draining",
    _ST_ERROR: "error",
    _ST_TIMEOUT: "timeout",
}


class ServeRequestError(RuntimeError):
    """The fleet client exhausted its deadline/retry budget without one
    OK answer. Carries the per-attempt refusals for the postmortem."""

    def __init__(self, msg: str, rejects: List[Tuple[int, str, str]]):
        super().__init__(msg)
        self.rejects = rejects  # (follower rank, status name, detail)


# ---- host-local shared staging ---------------------------------------------


class FleetStage:
    """Mirror the origin's published chain into ``fleet_stage_dir`` once.

    ``stage_once`` is idempotent: links already mirrored and CRC-clean are
    skipped, a half-copied link from a previous torn attempt is replaced,
    and the stage's own ``latest.json`` is published (atomically) only
    after the whole chain verifies — followers tailing the stage can never
    observe a partial version. One stager serves any number of followers:
    ``serve.fleet_stage_fetches`` counts mirrored snapshots, independent
    of fleet size (the "single disk fetch" claim, pinned by tests).
    """

    def __init__(self, origin_root: str, stage_dir: Optional[str] = None):
        self.origin = origin_root
        self.stage_dir = stage_dir or str(config.get_flag("fleet_stage_dir"))
        if not self.stage_dir:
            raise ValueError(
                "FleetStage needs a stage directory: pass stage_dir or set "
                "the fleet_stage_dir flag"
            )
        os.makedirs(self.stage_dir, exist_ok=True)
        self.require_manifest = bool(config.get_flag("serve_require_manifest"))

    # -- internals ---------------------------------------------------------

    def _mirror_snapshot(self, rel: str, want_crc) -> bool:
        """Copy one snapshot dir origin -> stage; returns True when bytes
        moved. Present-and-verified links are skipped (idempotent retry);
        a stale/torn copy is replaced wholesale."""
        dst = os.path.join(self.stage_dir, rel)
        if os.path.isdir(dst) and verify_chain_link(
            self.stage_dir, rel, want_crc, self.require_manifest
        ):
            return False
        tmp = os.path.join(self.stage_dir, rel + ".staging")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        os.makedirs(os.path.dirname(tmp) or self.stage_dir, exist_ok=True)
        shutil.copytree(os.path.join(self.origin, rel), tmp)
        os.replace(tmp, dst)
        if not verify_chain_link(self.stage_dir, rel, want_crc, self.require_manifest):
            raise RuntimeError(
                f"staged snapshot {rel!r} failed CRC verification after "
                "mirror — origin bytes changed mid-copy or disk fault"
            )
        return True

    def _mirror_dense(self, wm: Dict[str, Any]) -> bool:
        dense = wm.get("dense")
        if dense is None:
            return False
        rel, want = dense["path"], dense.get("crc32")
        dst = os.path.join(self.stage_dir, rel)
        if os.path.exists(dst) and (want is None or _file_crc32(dst) == want):
            return False
        src = os.path.join(self.origin, rel)
        if not os.path.exists(src):
            return False  # follower's own dense-skip alarm handles it
        os.makedirs(os.path.dirname(dst) or self.stage_dir, exist_ok=True)
        tmp = dst + ".staging"
        shutil.copyfile(src, tmp)
        if want is not None and _file_crc32(tmp) != want:
            raise RuntimeError(
                f"staged dense file {rel!r} failed CRC after mirror"
            )
        os.replace(tmp, dst)
        return True

    # -- public surface ----------------------------------------------------

    def stage_once(self) -> bool:
        """One origin poll; returns True when the stage watermark advanced.

        Raises on any mirror fault (including the injected
        ``serve.fleet_stage`` site) — the caller's loop counts and
        retries; the stage watermark is only written on full success, so
        followers never see a partial chain.
        """
        wm = read_watermark(self.origin)
        if wm is None:
            return False
        validate_watermark(wm)
        if read_watermark(self.stage_dir) == wm:
            return False  # stage is current
        fire("serve.fleet_stage")
        idx = int(wm["delta_idx"])
        fetched = 0
        fetched += self._mirror_snapshot(
            wm["base"]["path"], wm["base"].get("manifest_crc")
        )
        for entry in wm["deltas"][:idx]:
            fetched += self._mirror_snapshot(
                entry["path"], entry.get("manifest_crc")
            )
        fetched += self._mirror_dense(wm)
        with atomic_write(os.path.join(self.stage_dir, "latest.json")) as f:
            json.dump(wm, f)
        if fetched:
            STAT_ADD("serve.fleet_stage_fetches", fetched)
        STAT_SET("serve.fleet_stage_delta_idx", idx)
        return True

    def run(self, stop: threading.Event, interval_s: Optional[float] = None) -> None:
        """Stager loop with alarm-and-keep-staging semantics (same contract
        as Follower.run: a bad origin publish must not kill the host)."""
        interval = (
            config.get_flag("serve_poll_interval_s")
            if interval_s is None
            else interval_s
        )
        while not stop.is_set():
            try:
                self.stage_once()
            except Exception as e:  # noqa: BLE001 — staging must outlive faults
                STAT_ADD("serve.fleet_stage_errors")
                logger.error(
                    "fleet stage fetch failed (stage watermark unchanged, "
                    "followers keep serving last staged version): %s", e,
                )
            stop.wait(interval)


# ---- follower-side: request serving + gossip -------------------------------


class FleetFollower:
    """One serving rank: a Follower + ScoreServer behind PBTX framing.

    Threads: a request loop (recv ``serve:req`` → answer queue), a small
    answer pool (waits on the batcher future, sends ``serve:resp``), a
    health-gossip loop, and (optionally) the follower's own poll loop.
    ``drain``/``admit`` commands arrive on ``ctl:serve:drain`` and are
    handled inside the request loop, so drain state and request admission
    are ordered by construction.
    """

    _N_ANSWERERS = 4

    def __init__(
        self,
        transport,
        client_rank: int,
        follower: Follower,
        scorer: Scorer,
        schema,
        poll_interval_s: Optional[float] = None,
    ):
        self.tp = transport
        self.client_rank = int(client_rank)
        self.follower = follower
        self.schema = schema
        self.server = ScoreServer(follower, scorer, schema)
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._inflight = 0  # guarded-by: _iflock
        self._iflock = threading.Lock()
        self._work: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._threads: List[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self, poll: bool = True) -> None:
        self.server.start()
        targets = [self._request_loop, self._health_loop] + [
            self._answer_loop
        ] * self._N_ANSWERERS
        if poll:
            targets.append(
                lambda: self.follower.run(self._stop, self.poll_interval_s)
            )
        for fn in targets:
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for _ in range(self._N_ANSWERERS):
            self._work.put(None)
        for t in self._threads:
            t.join(timeout=10)
        self.server.stop()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def inflight(self) -> int:
        with self._iflock:
            return self._inflight

    # -- request path ------------------------------------------------------

    def _request_loop(self) -> None:
        while not self._stop.is_set():
            self._poll_drain()
            try:
                payload = self.tp.recv(_REQ_TAG, self.client_rank, timeout=0.2)
            except TimeoutError:
                continue
            except ConnectionError:
                # client link down (incl. PeerDeadError) — keep serving,
                # the front-end reconnects or a new one dials in
                STAT_ADD("serve.request_loop_errors")
                self._stop.wait(0.2)
                continue
            try:
                fire("serve.request_recv")
                req = json.loads(payload.decode("utf-8"))
                rid = int(req["id"])
            except Exception as e:  # noqa: BLE001 — a lost request is the client's retry
                STAT_ADD("serve.request_recv_errors")
                logger.error("serve request dropped at recv: %s", e)
                continue
            if self._draining.is_set():
                STAT_ADD("serve.drain_refused")
                self._reply(rid, _ST_DRAINING, detail="follower draining")
                continue
            with self._iflock:
                self._inflight += 1
            self._work.put(req)

    def _answer_loop(self) -> None:
        while True:
            req = self._work.get()
            if req is None:
                return
            try:
                self._answer(req)
            finally:
                with self._iflock:
                    self._inflight -= 1

    def _answer(self, req: dict) -> None:
        rid = int(req["id"])
        budget_s = max(0.0, float(req.get("deadline_ms", 0.0))) / 1000.0 or None
        try:
            records = [parse_line(ln, self.schema) for ln in req["lines"]]
            pending = self.server.submit(records)
            preds = pending.result(budget_s)
        except ServeOverloadError as e:
            self._reply(rid, _ST_OVERLOAD, detail=str(e))
            return
        except ServeTimeoutError as e:
            self._reply(rid, _ST_TIMEOUT, detail=str(e))
            return
        except Exception as e:  # noqa: BLE001 — typed on the wire, client retries
            STAT_ADD("serve.request_errors")
            self._reply(rid, _ST_ERROR, detail=repr(e))
            return
        self._reply(rid, _ST_OK, delta_idx=pending.delta_idx, preds=preds)

    def _reply(
        self,
        rid: int,
        status: int,
        delta_idx: int = -1,
        preds: Optional[np.ndarray] = None,
        detail: str = "",
    ) -> None:
        if status == _ST_OK:
            body = np.asarray(preds, dtype=np.float32).tobytes()
            n = len(preds)
        else:
            body = detail.encode("utf-8")
            n = 0
        try:
            self.tp.send(
                self.client_rank,
                _RESP_TAG,
                _RESP.pack(rid, status, delta_idx, n) + body,
            )
            STAT_ADD("serve.fleet_responses")
        except (ConnectionError, OSError) as e:
            # client gone mid-request: its retry/hedge already covers this
            STAT_ADD("serve.response_send_errors")
            logger.error("serve response %s dropped: %s", rid, e)

    # -- drain protocol ----------------------------------------------------

    def _poll_drain(self) -> None:
        if self.client_rank not in self.tp.pending_sources(_DRAIN_TAG):
            return
        try:
            payload = self.tp.recv(_DRAIN_TAG, self.client_rank, timeout=1.0)
        except (TimeoutError, ConnectionError):
            STAT_ADD("serve.drain_errors")
            return
        try:
            fire("serve.drain")
            action = json.loads(payload.decode("utf-8"))["action"]
        except Exception as e:  # noqa: BLE001 — dropped command, client re-sends
            STAT_ADD("serve.drain_errors")
            logger.error("drain command dropped (client will re-send): %s", e)
            return
        if action == "drain":
            if not self._draining.is_set():
                self._draining.set()
                STAT_ADD("serve.drains")
                logger.info("follower draining: finishing in-flight, refusing new")
        elif action == "admit":
            if self._draining.is_set():
                self._draining.clear()
                STAT_ADD("serve.drain_admits")
                logger.info("follower re-admitted to rotation")
        # announce the (possibly unchanged — idempotent) state right away
        self._beat()

    # -- health gossip -----------------------------------------------------

    def _state(self) -> str:
        snap = self.follower.health_snapshot()
        if self._draining.is_set():
            if self.inflight() == 0 and self.server.queue_depth() == 0:
                return "drained"
            return "draining"
        if not snap["warm"]:
            return "cold"
        if snap["reanchoring"]:
            return "reanchor"
        return "ready"

    def _beat(self) -> None:
        beat = dict(self.follower.health_snapshot())
        beat["state"] = self._state()
        beat["queue_depth"] = self.server.queue_depth()
        beat["inflight"] = self.inflight()
        try:
            self.tp.send(
                self.client_rank, _HEALTH_TAG, json.dumps(beat).encode("utf-8")
            )
            STAT_ADD("serve.health_beats")
        except (ConnectionError, OSError):
            STAT_ADD("serve.health_beat_errors")

    def _health_loop(self) -> None:
        interval = float(config.get_flag("serve_health_beat_s"))
        while not self._stop.is_set():
            self._beat()
            self._stop.wait(interval)


# ---- client-side: health view + load balancing -----------------------------


class FleetView:
    """Per-follower health bookkeeping, fed by ctl:serve:health beats.

    ``status`` is the follower-health state machine (docs/SERVING.md):
    never/dead (no or stale gossip), cold (no served params yet),
    draining/drained (explicit drain protocol), reanchor (mid ownership-
    epoch re-anchor, or an epoch behind the fleet), lagging (delta_idx
    more than ``serve_lag_deltas`` behind the freshest same-epoch
    follower), penalized (recent refusal/send failure, short cooldown),
    ready (queryable). Only "ready" followers are routed to.
    """

    def __init__(self, ranks: Sequence[int]):
        self.ranks = [int(r) for r in ranks]
        self._lock = threading.Lock()
        self._beats: Dict[int, dict] = {}  # guarded-by: _lock
        self._t_beat: Dict[int, float] = {}  # guarded-by: _lock
        self._penalty_until: Dict[int, float] = {}  # guarded-by: _lock
        self._drain_intent: set = set()  # guarded-by: _lock
        self._rr = 0  # guarded-by: _lock
        # (epoch, delta_idx, staleness_s) per rank, appended whenever the
        # gossiped chain position advances — the staleness gauge tests pin
        # monotone-per-version on this log
        self.staleness_log: Dict[int, List[Tuple[int, int, float]]] = {}

    def observe(self, rank: int, beat: dict) -> None:
        rank = int(rank)
        with self._lock:
            prev = self._beats.get(rank)
            self._beats[rank] = beat
            self._t_beat[rank] = time.monotonic()
            pos = (int(beat.get("ownership_epoch", 0)), int(beat.get("delta_idx", -1)))
            if beat.get("staleness_s") is not None and (
                prev is None
                or (int(prev.get("ownership_epoch", 0)),
                    int(prev.get("delta_idx", -1))) < pos
            ):
                self.staleness_log.setdefault(rank, []).append(
                    (pos[0], pos[1], float(beat["staleness_s"]))
                )
        STAT_SET("serve.fleet_queryable", len(self.queryable()))

    def set_drain_intent(self, rank: int, draining: bool) -> None:
        """Operator intent: marked out of rotation immediately, before the
        follower's own gossip confirms."""
        with self._lock:
            if draining:
                self._drain_intent.add(int(rank))
            else:
                self._drain_intent.discard(int(rank))

    def penalize(self, rank: int, seconds: float) -> None:
        with self._lock:
            self._penalty_until[int(rank)] = max(
                self._penalty_until.get(int(rank), 0.0),
                time.monotonic() + seconds,
            )

    # -- status ------------------------------------------------------------

    def _statuses(self) -> Dict[int, str]:
        """One consistent pass over every rank under one lock hold (the
        lock is non-reentrant, so all guarded reads live here)."""
        dead_s = float(config.get_flag("serve_health_dead_s"))
        lag_deltas = int(config.get_flag("serve_lag_deltas"))
        with self._lock:
            now = time.monotonic()
            fresh = [
                r for r in self.ranks
                if r in self._t_beat and now - self._t_beat[r] <= dead_s
            ]
            epochs = [int(self._beats[r].get("ownership_epoch", 0)) for r in fresh]
            emax = max(epochs) if epochs else 0
            dmax = max(
                (
                    int(self._beats[r].get("delta_idx", -1))
                    for r in fresh
                    if int(self._beats[r].get("ownership_epoch", 0)) == emax
                ),
                default=-1,
            )
            out: Dict[int, str] = {}
            for rank in self.ranks:
                if rank in self._drain_intent:
                    out[rank] = "draining"
                    continue
                t = self._t_beat.get(rank)
                if t is None:
                    out[rank] = "never"
                    continue
                if now - t > dead_s:
                    out[rank] = "dead"
                    continue
                b = self._beats[rank]
                state = b.get("state", "ready")
                if state in ("draining", "drained"):
                    out[rank] = state
                elif state == "cold" or not b.get("warm"):
                    out[rank] = "cold"
                elif state == "reanchor" or b.get("reanchoring"):
                    out[rank] = "reanchor"
                elif int(b.get("ownership_epoch", 0)) < emax:
                    # behind an ownership-epoch flip the rest of the fleet
                    # already applied: out of rotation until its own
                    # re-anchor lands
                    out[rank] = "reanchor"
                elif int(b.get("delta_idx", -1)) < dmax - lag_deltas:
                    out[rank] = "lagging"
                elif now < self._penalty_until.get(rank, 0.0):
                    out[rank] = "penalized"
                else:
                    out[rank] = "ready"
            return out

    def status(self, rank: int) -> str:
        return self._statuses()[int(rank)]

    def queryable(self) -> List[int]:
        statuses = self._statuses()
        return [r for r in self.ranks if statuses[r] == "ready"]

    def pick(self, avoid: Sequence[int] = ()) -> Optional[int]:
        """Round-robin over queryable followers, preferring ones not in
        ``avoid``; falls back to an avoided-but-queryable one rather than
        failing (retrying the same follower beats not retrying).

        With ``serve_lb_least_loaded`` on, the round-robin choice is
        weighed against the NEXT rotation candidate by the queue depth
        each follower last gossiped (least-loaded-of-two: near-uniform
        spread when depths tie, hot-spot avoidance when they don't);
        taking the second candidate over the rotation's own is counted
        under ``serve.lb_rerouted``. Flag off is the pure round-robin
        ablation, bitwise the historical pick order."""
        q = self.queryable()
        if not q:
            return None
        preferred = [r for r in q if r not in set(avoid)] or q
        with self._lock:
            self._rr += 1
            first = preferred[self._rr % len(preferred)]
            if len(preferred) < 2 or not config.get_flag(
                "serve_lb_least_loaded"
            ):
                return first
            second = preferred[(self._rr + 1) % len(preferred)]
            b1 = self._beats.get(first)
            b2 = self._beats.get(second)
            d1 = 0 if b1 is None else int(b1.get("queue_depth", 0))
            d2 = 0 if b2 is None else int(b2.get("queue_depth", 0))
            if d2 < d1:
                STAT_ADD("serve.lb_rerouted")
                return second
            return first

    def snapshot(self) -> Dict[int, str]:
        return self._statuses()

    def gossip_state(self, rank: int) -> Optional[str]:
        """The state the follower ITSELF last gossiped (None before any
        beat). Unlike :meth:`status` this ignores the operator's drain
        intent — it is the drain protocol's confirmation signal, so it
        must reflect only what the follower announced."""
        with self._lock:
            b = self._beats.get(int(rank))
            return None if b is None else b.get("state")


class _ClientPending:
    """One in-flight client request: outcomes from every dispatched copy
    (primary + hedge + retries share the request id)."""

    def __init__(self) -> None:
        self.cv = threading.Condition()
        self.ok: Optional[dict] = None  # guarded-by: cv
        self.rejects: List[Tuple[int, str, str]] = []  # guarded-by: cv
        self.dispatched = 0  # guarded-by: cv

    def add(self, src: int, status: int, resp: dict) -> bool:
        """Record one response; returns False for a duplicate OK (a lost
        hedge race)."""
        with self.cv:
            if status == _ST_OK:
                if self.ok is not None:
                    return False
                self.ok = resp
            else:
                self.rejects.append(
                    (src, _ST_NAMES.get(status, str(status)), resp.get("detail", ""))
                )
            self.cv.notify_all()
            return True

    def wait(self, deadline: float) -> Optional[dict]:
        """Block until an OK lands, every dispatched copy has been
        rejected, or ``deadline`` (monotonic). Returns the OK or None."""
        with self.cv:
            while True:
                if self.ok is not None:
                    return self.ok
                if self.dispatched and len(self.rejects) >= self.dispatched:
                    return None
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self.cv.wait(min(left, 0.1))


class FleetClient:
    """Load-balancing, deadline-enforcing, hedging front-end client.

    One response thread and one gossip thread multiplex ALL followers via
    ``recv_first`` — responses carry the request id, so hedged duplicates
    and post-deadline stragglers resolve (or are counted away) without
    any per-follower thread fan-out.
    """

    def __init__(self, transport, follower_ranks: Sequence[int], schema=None):
        self.tp = transport
        self.ranks = [int(r) for r in follower_ranks]
        self.schema = schema
        self.view = FleetView(self.ranks)
        self.latency_hist = Histogram()
        self._lock = threading.Lock()
        self._pending: Dict[int, _ClientPending] = {}  # guarded-by: _lock
        self._next_id = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._marked_dead: set = set()  # ranks we confirmed dead to the transport

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for fn in (self._resp_loop, self._gossip_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def _sync_membership(self) -> None:
        """Mirror the health view into transport membership: a follower
        whose gossip went silent is confirmed dead to the transport, which
        is what arms the HELLO delivered-count reset — without it a NEW
        incarnation rejoining at the same rank would have all its frames
        eaten as replay duplicates of the old stream."""
        statuses = self.view.snapshot()
        for rank, status in statuses.items():
            if status == "dead" and rank not in self._marked_dead:
                self._marked_dead.add(rank)
                self.tp.mark_dead([rank])
                STAT_ADD("serve.fleet_deaths")
                logger.warning("follower %s confirmed dead (gossip silent)", rank)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)

    # -- receive loops -----------------------------------------------------

    def _resp_loop(self) -> None:
        while not self._stop.is_set():
            try:
                src, payload = self.tp.recv_first(_RESP_TAG, self.ranks, timeout=0.2)
            except TimeoutError:
                continue
            except ConnectionError:
                # every follower dead by the detector — keep polling, a
                # rejoin resets last_seen and the fleet comes back
                self._stop.wait(0.2)
                continue
            rid, status, delta_idx, n = _RESP.unpack_from(payload)
            body = payload[_RESP.size:]
            if status == _ST_OK:
                resp = {
                    "src": src,
                    "delta_idx": int(delta_idx),
                    "preds": np.frombuffer(body, dtype=np.float32, count=n).copy(),
                }
            else:
                resp = {"src": src, "detail": body.decode("utf-8", "replace")}
            with self._lock:
                pending = self._pending.get(rid)
            if pending is None:
                STAT_ADD("serve.late_responses")
                continue
            if not pending.add(src, status, resp):
                STAT_ADD("serve.hedge_wasted")

    def _gossip_loop(self) -> None:
        while not self._stop.is_set():
            try:
                src, payload = self.tp.recv_first(_HEALTH_TAG, self.ranks, timeout=0.2)
            except TimeoutError:
                self._sync_membership()
                continue
            except ConnectionError:
                self._stop.wait(0.2)
                continue
            try:
                beat = json.loads(payload.decode("utf-8"))
            except ValueError:
                STAT_ADD("serve.health_beat_errors")
                continue
            if src in self._marked_dead:
                # gossip resumed from a rank we confirmed dead: a new
                # incarnation joined at that slot — readmit it
                self._marked_dead.discard(src)
                self.tp.mark_alive(src)
                STAT_ADD("serve.fleet_rejoins")
                logger.info("follower %s rejoined (gossip resumed)", src)
            self.view.observe(src, beat)
            self._sync_membership()

    # -- request path ------------------------------------------------------

    def _register(self) -> Tuple[int, _ClientPending]:
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            pending = _ClientPending()
            self._pending[rid] = pending
            return rid, pending

    def _unregister(self, rid: int) -> None:
        with self._lock:
            self._pending.pop(rid, None)

    def _dispatch(self, rank: int, pending: _ClientPending, payload: bytes) -> bool:
        try:
            self.tp.send(rank, _REQ_TAG, payload)
        except (ConnectionError, OSError) as e:
            STAT_ADD("serve.client_send_errors")
            self.view.penalize(rank, 1.0)
            logger.warning("dispatch to follower %s failed: %s", rank, e)
            return False
        with pending.cv:
            pending.dispatched += 1
        return True

    def score_lines(
        self, lines: Sequence[str], timeout: Optional[float] = None
    ) -> Tuple[np.ndarray, dict]:
        """Score raw slot-format lines; returns (preds, meta) with
        ``meta["delta_idx"]``/``meta["src"]``. Deadline, bounded-backoff
        retry across followers, and hedged re-dispatch all live here; the
        typed :class:`ServeRequestError` surfaces only after the whole
        budget is spent."""
        if timeout is None:
            timeout = float(config.get_flag("serve_request_timeout_ms")) / 1000.0
        retries = int(config.get_flag("serve_client_retries"))
        backoff = float(config.get_flag("serve_client_backoff_s"))
        hedge_s = float(config.get_flag("serve_hedge_ms")) / 1000.0
        t0 = time.monotonic()
        t_end = t0 + timeout
        rid, pending = self._register()
        STAT_ADD("serve.client_requests")
        avoid: set = set()
        hedges = 0
        try:
            for attempt in range(retries + 1):
                if attempt:
                    STAT_ADD("serve.client_retries")
                    delay = min(
                        backoff * (2 ** (attempt - 1)),
                        max(0.0, t_end - time.monotonic()),
                    )
                    if delay > 0:
                        time.sleep(delay)
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    break
                target = self.view.pick(avoid=avoid)
                if target is None:
                    # no queryable follower right now — burn a retry slot
                    # waiting for gossip to readmit one
                    continue
                payload = json.dumps({
                    "id": rid,
                    "deadline_ms": remaining * 1000.0,
                    "lines": list(lines),
                }).encode("utf-8")
                if not self._dispatch(target, pending, payload):
                    avoid.add(target)
                    continue
                wait_until = (
                    t_end if hedge_s <= 0
                    else min(t_end, time.monotonic() + hedge_s)
                )
                ok = pending.wait(wait_until)
                if ok is None and hedge_s > 0 and time.monotonic() < t_end:
                    with pending.cv:
                        answered = pending.dispatched <= len(pending.rejects)
                    if not answered:
                        # primary silent past the hedge budget: race a
                        # second follower, first answer wins
                        second = self.view.pick(avoid=avoid | {target})
                        if second is not None and second != target:
                            if self._dispatch(second, pending, payload):
                                hedges += 1
                                STAT_ADD("serve.hedges")
                    ok = pending.wait(t_end)
                if ok is not None:
                    lat_ms = (time.monotonic() - t0) * 1000.0
                    self.latency_hist.observe(lat_ms)
                    STAT_OBSERVE("serve.client_latency_ms", lat_ms)
                    return ok["preds"], {
                        "src": ok["src"],
                        "delta_idx": ok["delta_idx"],
                        "latency_ms": lat_ms,
                        "attempts": attempt + 1,
                        "hedges": hedges,
                    }
                # every dispatched copy refused (or deadline loomed):
                # penalize refusers briefly and go around
                with pending.cv:
                    rejects = list(pending.rejects)
                for src, _name, _detail in rejects:
                    avoid.add(src)
                    self.view.penalize(src, 0.5)
            STAT_ADD("serve.client_failures")
            with pending.cv:
                rejects = list(pending.rejects)
            raise ServeRequestError(
                f"score request {rid} failed after {retries + 1} attempts "
                f"within {timeout:.1f}s (rejections: "
                f"{[(s, n) for s, n, _ in rejects]})",
                rejects,
            )
        finally:
            self._unregister(rid)

    # -- drain orchestration ----------------------------------------------

    def _drain_cmd(
        self, rank: int, action: str, confirm_states: Tuple[str, ...],
        wait_s: float,
    ) -> bool:
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            try:
                self.tp.send(
                    rank, _DRAIN_TAG,
                    json.dumps({"action": action}).encode("utf-8"),
                )
                STAT_ADD("serve.drain_commands")
            except (ConnectionError, OSError):
                STAT_ADD("serve.client_send_errors")
            # commands are idempotent: re-send until the follower's OWN
            # gossip confirms (a dropped command — e.g. the serve.drain
            # fault site — heals on the next lap)
            confirm_by = min(deadline, time.monotonic() + 0.5)
            while time.monotonic() < confirm_by:
                if self.view.gossip_state(rank) in confirm_states:
                    return True
                time.sleep(0.02)
        return False

    def drain(self, rank: int, wait_s: float = 10.0) -> bool:
        """Explicit drain: mark out of rotation NOW, then command the
        follower (finish in-flight, refuse new) and wait for its gossip
        to announce the drain. Idempotent; returns confirmation."""
        self.view.set_drain_intent(rank, True)
        return self._drain_cmd(rank, "drain", ("draining", "drained"), wait_s)

    def admit(self, rank: int, wait_s: float = 10.0) -> bool:
        """Readmit a drained follower to rotation (confirmed by gossip).
        The operator mark is lifted first — until the follower's own beat
        stops saying "draining" the view still keeps it out, so routing
        only resumes once BOTH sides agree."""
        self.view.set_drain_intent(rank, False)
        return self._drain_cmd(rank, "admit", ("ready", "cold", "reanchor"), wait_s)

    # -- reporting ---------------------------------------------------------

    def latency_percentiles(self) -> dict:
        h = self.latency_hist
        if h.count == 0:
            return {"n": 0}
        p50, p99 = h.quantiles((0.5, 0.99))
        return {
            "n": h.count,
            "p50_ms": float(p50),
            "p99_ms": float(p99),
            "max_ms": float(h.max),
        }
