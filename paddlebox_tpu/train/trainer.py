"""Pass-loop trainer: the BoxPSTrainer/BoxPSWorker + Executor analog.

The reference drives training through Executor::RunFromDataset spawning one
BoxPSWorker thread per GPU (boxps_trainer.cc:186-200); here one CTRTrainer
owns the jitted step (single-device or mesh — the mesh step already contains
every device's work) and walks a BoxPSDataset pass by pass:

    trainer = CTRTrainer(model, cfg, plan=...)
    dataset.load_into_memory(); dataset.begin_pass()
    metrics = trainer.train_pass(dataset)
    # single-process: hand the DEVICE table over — the boundary then goes
    # delta-only (table/carrier.py); multi-host uses trained_table()
    dataset.end_pass(trainer.trained_table_device(), need_save_delta=...)

Dense params/optimizer state persist across passes on device; the sparse
working-set table is rebuilt per pass (pass-scoped HBM staging parity).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from paddlebox_tpu.data.dataset import BoxPSDataset
from paddlebox_tpu.fleet.zero import Zero1Optimizer
from paddlebox_tpu.data.device_pack import BatchPacker, pack_batch, pack_batch_sharded
from paddlebox_tpu.data.pipeline import prefetch
from paddlebox_tpu.metrics.auc import auc_compute, auc_init
from paddlebox_tpu.metrics.registry import MetricRegistry
from paddlebox_tpu.parallel.mesh import (
    MeshPlan,
    local_slice,
    put_replicated,
    put_sharded,
)
from paddlebox_tpu.train.sharded_step import (
    init_sharded_train_state,
    kstep_sync_params,
    make_sharded_train_step,
)
from paddlebox_tpu.train.resident_step import (
    ResidentPass,
    make_resident_superstep,
)
from paddlebox_tpu.train.train_step import (
    TrainState,
    TrainStepConfig,
    jit_train_step,
    make_train_step,
)
from paddlebox_tpu.utils.dump import DumpWorkerPool, dump_fields, dump_param
from paddlebox_tpu.utils.faultinject import fire as _fault_fire
from paddlebox_tpu.utils.fs import atomic_write
from paddlebox_tpu.utils.trace import PROFILER
from paddlebox_tpu import config

config.define_flag(
    "max_inflight_steps",
    4,
    "cap on dispatched-but-unfinished device steps; 0 = unbounded. Keeps "
    "the async dispatch queue shallow: enough depth to hide host->device "
    "round-trip latency behind compute, shallow enough that transfers and "
    "executions don't pile up on the transport (an unbounded queue measured "
    "3x slower end-to-end on a tunneled TPU than a depth-2 window)",
)


class CTRTrainer:
    def __init__(
        self,
        model: Any,  # object with .init(rng) / .apply(params, slot_feats, dense)
        cfg: TrainStepConfig,
        dense_opt: Optional[optax.GradientTransformation] = None,
        plan: Optional[MeshPlan] = None,
        dense_slot: Optional[str] = None,
        dense_dim: int = 0,
        pack_bucket: Optional[int] = None,
        metric_registry: Optional["MetricRegistry"] = None,
        async_dense: Optional["AsyncDenseTable"] = None,
        dump_pool: Optional["DumpWorkerPool"] = None,
        dump_fields_list: Sequence[str] = ("preds", "labels"),
        dump_mode: int = 0,  # 0 all, 1 sample-by-ins-id-hash, 2 every Nth batch
        dump_interval: int = 1,
        dump_params_at_end: bool = False,
        box: Optional[Any] = None,  # BoxWrapper whose test_mode gates eval
    ):
        self.model = model
        self.cfg = cfg
        self.dense_opt = dense_opt or optax.adam(1e-3)
        self.plan = plan
        self.async_dense = async_dense
        if isinstance(self.dense_opt, Zero1Optimizer) and plan is None:
            raise ValueError(
                "Zero1Optimizer (sharding strategy) needs a mesh plan — its "
                "optimizer state lives sharded across devices"
            )
        if cfg.dense_sync_mode == "async":
            if async_dense is None:
                raise ValueError(
                    "dense_sync_mode='async' needs an AsyncDenseTable (else "
                    "dense params would silently never update)"
                )
            if plan is not None and jax.process_count() > 1:
                # each process would push globally-reduced grads into its
                # own host table: consistent only under bit-identical update
                # rules AND lossless comms — not a guarantee worth making
                raise NotImplementedError(
                    "async dense mode spans one process (single-device or "
                    "single-host mesh); multi-host meshes use 'step'/'kstep'"
                )
        self.dense_slot = dense_slot
        self.dense_dim = dense_dim
        self.pack_bucket = pack_bucket
        self.metric_registry = metric_registry
        # per-batch field/param debug dumps (DeviceWorker::DumpField/DumpParam
        # parity, device_worker.cc:98-133; modes per device_worker.h:218-219)
        self.dump_pool = dump_pool
        self.dump_fields_list = tuple(dump_fields_list)
        self.dump_mode = dump_mode
        self.dump_interval = dump_interval
        self.dump_params_at_end = dump_params_at_end
        self.params: Any = None
        self.opt_state: Any = None
        self._state: Optional[TrainState] = None
        # eval/infer mode (SetTestMode box_wrapper.cc:623 +
        # infer_from_dataset executor.py:1520): either set directly on the
        # trainer or inherited from the owning BoxWrapper each pass
        self.box = box
        self.test_mode = False
        self._eval_step_cache = None
        if plan is None:
            self._step = jit_train_step(make_train_step(model.apply, self.dense_opt, cfg))
        else:
            self._step = make_sharded_train_step(model.apply, self.dense_opt, cfg, plan)

    # ---- eval mode -------------------------------------------------------

    def set_test_mode(self, on: bool = True) -> None:
        """SetTestMode parity: the next train_pass runs forward+metrics only
        (no sparse push, no dense update) until cleared."""
        self.test_mode = on

    @property
    def _eval_active(self) -> bool:
        return self.test_mode or bool(self.box is not None and self.box.test_mode)

    def _eval_step(self):
        if self._eval_step_cache is None:
            if self.plan is None:
                self._eval_step_cache = jit_train_step(
                    make_train_step(
                        self.model.apply, self.dense_opt, self.cfg, eval_mode=True
                    )
                )
            else:
                self._eval_step_cache = make_sharded_train_step(
                    self.model.apply, self.dense_opt, self.cfg, self.plan,
                    eval_mode=True,
                )
        return self._eval_step_cache

    # ---- dense param lifecycle ------------------------------------------

    def init_params(self, rng=None) -> None:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.params = self.model.init(rng)
        if isinstance(self.dense_opt, Zero1Optimizer):
            # chunked state is built (and placed sharded) by
            # init_sharded_train_state at pass start
            self.opt_state = None
        else:
            self.opt_state = self.dense_opt.init(self.params)

    def save_dense(self, path: str) -> None:
        """Dense checkpoint (worker-scope param dump parity,
        boxps_trainer.cc:123-131). Written tmp-then-rename so a crash
        mid-write can't corrupt the checkpoint a cursor already points to."""
        path = path if path.endswith(".npz") else path + ".npz"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        leaves, treedef = jax.tree.flatten((self.params, self.opt_state))
        with atomic_write(path, "wb") as f:
            np.savez_compressed(
                f,
                treedef=str(treedef),
                **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)},
            )

    def load_dense(self, path: str) -> None:
        if self.params is None:
            raise RuntimeError("init_params first (defines the tree structure)")
        if self.opt_state is None and isinstance(self.dense_opt, Zero1Optimizer):
            # rebuild the chunked-state structure so the checkpoint's zero
            # moment leaves have somewhere to land (fresh-process resume)
            self.opt_state = self.dense_opt.init_stacked(self.params)
        path = path if path.endswith(".npz") else path + ".npz"
        data = np.load(path, allow_pickle=False)
        leaves, treedef = jax.tree.flatten((self.params, self.opt_state))
        n_saved = sum(1 for k in data.files if k.startswith("leaf_"))
        if n_saved != len(leaves):
            raise ValueError(
                f"checkpoint holds {n_saved} leaves but the current "
                f"(params, opt_state) tree has {len(leaves)} — optimizer "
                "state mismatch (e.g. ZeRO chunked state not yet built: "
                "restore it with the same opt_state structure it was saved "
                "with, or load before switching optimizers)"
            )
        loaded = [jnp.asarray(data[f"leaf_{i}"]) for i in range(len(leaves))]
        for a, b in zip(leaves, loaded):
            if a.shape != b.shape:
                raise ValueError(f"dense checkpoint shape mismatch {a.shape} vs {b.shape}")
        self.params, self.opt_state = jax.tree.unflatten(treedef, loaded)

    # ---- pass loop -------------------------------------------------------

    def _make_state(self, dev_table: np.ndarray, ws_key: Optional[int] = None) -> TrainState:
        # within one pass (same working set), later train_pass calls — the
        # update phase after join, extra epochs, eval — must see the rows
        # the earlier calls trained, exactly as the reference's device table
        # persists between phases (BeginPass..EndPass, box_wrapper.cc:
        # 615-651). Rebuild only when the working set changes.
        if (
            self._state is not None
            and ws_key is not None
            and getattr(self, "_state_ws", None) is ws_key
        ):
            return self._state
        self._state_ws = ws_key
        if self.params is None:
            self.init_params()
        if self.plan is None:
            flat = jnp.asarray(dev_table.reshape(-1, dev_table.shape[-1]))
            # device COPIES of params/opt_state: the step donates its state,
            # so handing self.params's own buffers over would delete them —
            # a mid-pass save_dense or an aborted pass would then read dead
            # arrays (init_sharded_train_state makes the same copies on
            # the mesh path)
            return TrainState(
                table=flat,
                params=jax.tree.map(jnp.copy, self.params),
                opt_state=jax.tree.map(jnp.copy, self.opt_state),
                auc=auc_init(self.cfg.auc_buckets),
                step=jnp.zeros((), jnp.int32),
            )
        return init_sharded_train_state(
            self.plan,
            dev_table,
            self.params,
            self.dense_opt,
            self.cfg.auc_buckets,
            opt_state=self.opt_state,
            local_dense=self.cfg.dense_sync_mode == "kstep",
        )

    @property
    def _n_pack_devices(self) -> int:
        """Devices THIS process packs batches for: all of them single-host,
        the local block of the global mesh multi-host."""
        return self.plan.n_devices // jax.process_count()

    def _host_np(self, x) -> np.ndarray:
        """Device array -> host numpy, gathering non-addressable shards
        across processes when the mesh spans hosts."""
        if getattr(x, "is_fully_addressable", True):
            return np.asarray(x)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))

    def _pack_and_put(self, batch, ws):
        if self.plan is None:
            db = pack_batch(
                batch,
                ws,
                self._schema,
                dense_slot=self.dense_slot,
                dense_dim=self.dense_dim,
                bucket=self.pack_bucket,
            )
            return {k: jnp.asarray(v) for k, v in db.as_dict().items()}
        # sticky pad floors per working set: K/L only ever grow, so the
        # sharded step keeps ONE compiled program across a pass's batches
        # (the slow/pv analog of BatchPacker's frozen shapes)
        if getattr(self, "_pads_ws", None) is not ws:
            self._pads_ws = ws
            self._pads = [-1, 0]  # [k_floor (-1 = headroom), l_floor]
        db = pack_batch_sharded(
            batch,
            ws,
            self._schema,
            self._n_pack_devices,
            dense_slot=self.dense_slot,
            dense_dim=self.dense_dim,
            bucket=self.pack_bucket,
            k_floor=self._pads[0],
            l_floor=self._pads[1],
        )
        self._pads = [db.req_ranks.shape[2], db.inverse.shape[1]]
        return {k: put_sharded(self.plan, v) for k, v in db.as_dict().items()}

    def _feed_aux(
        self, feed, batch=None, ins_weight=None, cmatch=None, rank=None, ins_ids=None
    ):
        """(device feed, registry aux) tuple for the step loop."""
        aux = {}
        if batch is not None:
            cmatch, rank = batch.cmatch, batch.rank
            if ins_ids is None:
                ins_ids = batch.ins_ids
        if cmatch is not None:
            aux["cmatch"] = cmatch
        if rank is not None:
            aux["rank"] = rank
        if ins_weight is not None:
            aux["ins_weight"] = ins_weight
        if ins_ids is not None:
            aux["ins_ids"] = ins_ids
        return feed, aux

    def _pv_lockstep(self, dataset, n_dev: int) -> int:
        """Multi-host join phase: equalize batch counts and pad shapes.

        The pv analog of the fast path's transport-locksteped freeze_shapes
        (compute_thread_batch_nccl parity, data_set.cc:2069-2135): (a)
        allreduce-max the local pv batch count — short hosts emit all-ghost
        batches; (b) allreduce-max the per-device L and per-(device, shard)
        request-bucket K over every local batch INCLUDING the ghost tail, and
        seed the sticky pack floors, so every host compiles the same mesh
        program and no collective ever sees mismatched shapes.
        Returns the global batch count (min_batches for pv_batches)."""
        from paddlebox_tpu.data.device_pack import _round_bucket

        cached = getattr(self, "_pv_lockstep_cache", None)
        if (
            cached is not None
            and cached[0] is dataset.pvs
            and cached[1] is dataset.ws
        ):
            # repeat join-phase calls over the same pvs/ws (warmup epoch,
            # join eval) skip the host re-pack sweep AND the allreduces —
            # re-entering the collectives alone would desync any host that
            # took the cache hit
            min_b, k_glob, l_glob = cached[2]
            self._pads_ws = dataset.ws
            self._pads = [k_glob, l_glob]
            return min_b
        tp = dataset.transport
        if tp is None:
            raise RuntimeError(
                "multi-host join-phase (pv) training needs a dataset "
                "transport to lockstep batch counts and pad shapes across "
                "hosts (pass transport= to BoxPSDataset)"
            )
        min_b = dataset.num_pv_batches(n_devices=n_dev, global_count=True)
        ws = dataset.ws
        cap, ns = ws.capacity, ws.n_mesh_shards
        bucket = self.pack_bucket or config.get_flag("batch_bucket_rounding")
        b = dataset.batch_size // n_dev

        def block_stats(recs, ghost, n_real):
            """(L, shard-bucket max) of one device block incl. ghost pad —
            ghosts repeat an existing record, so they add keys but no new
            unique rows beyond the ghost's own."""
            keys_parts = [r.u64_values for r in recs]
            if n_real < b and ghost is not None:
                keys_parts.extend([ghost.u64_values] * (b - n_real))
            keys = (
                np.concatenate(keys_parts)
                if keys_parts
                else np.zeros(0, np.uint64)
            )
            if not len(keys):
                return 0, 0
            uniq = np.unique(ws.lookup(keys))
            return len(keys), int(np.bincount(uniq // cap, minlength=ns).max())

        from paddlebox_tpu.data.pv_instance import (
            _iter_pv_blocks,
            first_pv_record,
            flatten_pv_instances,
        )

        max_L, max_bucket = 1, 0
        fallback = first_pv_record(dataset.pvs)
        n_local = 0
        for blocks in _iter_pv_blocks(dataset.pvs, b, n_dev):
            n_local += 1
            groups = list(blocks) + [[]] * (n_dev - len(blocks))
            # emit()'s ghost for an all-empty group is the first ad WITHIN
            # this batch (_GHOST_FALLBACK) — mirror it exactly so L matches
            batch_ghost = next(
                (pv.ads[0] for g in groups for pv in g if pv.ads), fallback
            )
            for group in groups:
                recs = flatten_pv_instances(group)
                ghost = recs[-1] if recs else batch_ghost
                L, bmax = block_stats(recs, ghost, len(recs))
                max_L = max(max_L, L)
                max_bucket = max(max_bucket, bmax)
        if n_local < min_b and fallback is not None:
            # lockstep all-ghost batches: b copies of one record per device
            L, bmax = block_stats([], fallback, 0)
            max_L = max(max_L, L)
            max_bucket = max(max_bucket, bmax)
        k_glob = tp.allreduce_max(
            _round_bucket(max_bucket + 1, bucket), f"pv-K:{dataset.pass_id}"
        )
        l_glob = tp.allreduce_max(
            _round_bucket(max_L, bucket), f"pv-L:{dataset.pass_id}"
        )
        self._pads_ws = dataset.ws
        self._pads = [k_glob, l_glob]
        self._pv_lockstep_cache = (dataset.pvs, dataset.ws, (min_b, k_glob, l_glob))
        return min_b

    def _pv_plan_feed_iter(self, dataset, plan, n_batches):
        """Plan-driven join-phase feed: the pv analog of _fast_feed_iter.

        Batch composition comes from the PvPlan's index tensor, so packing
        runs through the native columnar packer (BatchPacker) instead of
        the per-record SlotBatch path, with the same prefetch overlap as
        the flat fast path. On a multi-host mesh, freeze_shapes'
        transport branch locksteps the pads — replacing the per-record
        _pv_lockstep sweep with vectorized store math."""
        store = dataset.store
        packer = self._get_packer(dataset)
        n_dev = 1 if self.plan is None else self._n_pack_devices
        b = dataset.batch_size // n_dev
        packer.freeze_shapes(
            plan.idx,
            n_devices=n_dev if self.plan is not None else 0,
            transport=dataset.transport,
        )
        has_meta = store.ins_id_off is not None
        want_ids = has_meta and self.dump_pool is not None
        n = plan.n_batches
        if n_batches is not None:
            n = min(n, n_batches)

        def prep(pos):
            idx = plan.idx[pos]
            ro = plan.rank_offset[pos]
            w = plan.ins_weight[pos]
            if self.plan is None:
                db = packer.pack(idx)
                feed = {k: jax.device_put(v) for k, v in db.as_dict().items()}
                feed["ins_weight"] = jnp.asarray(w)
                feed["rank_offset"] = jnp.asarray(ro)
            else:
                db = packer.pack_sharded(idx, n_dev)
                feed = {
                    k: put_sharded(self.plan, v) for k, v in db.as_dict().items()
                }
                feed["ins_weight"] = put_sharded(self.plan, w.reshape(n_dev, b))
                feed["rank_offset"] = put_sharded(
                    self.plan, ro.reshape(n_dev, b, ro.shape[-1])
                )
            ids = [store.ins_id(int(j)) for j in idx] if want_ids else None
            return idx, feed, w, ids

        for idx, feed, w, ids in prefetch(range(n), prep):
            yield self._feed_aux(
                feed,
                ins_weight=w,
                cmatch=store.cmatch[idx] if has_meta else None,
                rank=store.rank[idx] if has_meta else None,
                ins_ids=ids,
            )

    def _pv_feed_iter(self, dataset, n_batches):
        n_dev = 1 if self.plan is None else self._n_pack_devices
        multi = self.plan is not None and jax.process_count() > 1
        if dataset.store is not None:
            plan, _ = self._pv_locked_plan(dataset)
            if plan is not None:
                yield from self._pv_plan_feed_iter(dataset, plan, n_batches)
                return
        min_b = 0
        if multi:
            min_b = self._pv_lockstep(dataset, n_dev)

        def prepare(item):
            batch, ins_weight = item
            feed = self._pack_and_put(batch, dataset.ws)
            if self.plan is None:
                if ins_weight is not None:
                    feed["ins_weight"] = jnp.asarray(ins_weight)
                if batch.rank_offset is not None:
                    feed["rank_offset"] = jnp.asarray(batch.rank_offset)
            else:
                # device-blocked pv batch: per-device leading axis, rank
                # offsets already device-local (pv_instance.pack_pv_batches)
                b = batch.batch_size // n_dev
                feed["ins_weight"] = put_sharded(
                    self.plan, ins_weight.reshape(n_dev, b)
                )
                ro = batch.rank_offset
                feed["rank_offset"] = put_sharded(
                    self.plan, ro.reshape(n_dev, b, ro.shape[-1])
                )
            return self._feed_aux(feed, batch=batch, ins_weight=ins_weight)

        # ONE worker, shallow depth: batch i+1 builds+packs while i trains
        # (join-phase analog of the fast path's prefetch). A single worker
        # keeps the sticky pad floors race-free and the order deterministic.
        yield from prefetch(
            dataset.pv_batches(n_batches, n_devices=n_dev, min_batches=min_b),
            prepare,
            workers=1,
            depth=2,
        )

    def _slow_feed_iter(self, dataset, n_batches):
        for batch in dataset.batches(n_batches):
            yield self._feed_aux(
                self._pack_and_put(batch, dataset.ws), batch=batch
            )

    def _get_packer(self, dataset) -> BatchPacker:
        """One BatchPacker per (store, working set): keeps pad shapes — and
        thus the compiled device program — stable across train_pass calls
        within a pass (warmup + epochs share one XLA executable)."""
        cached = getattr(self, "_packer_cache", None)
        if (
            cached is not None
            and cached[0] is dataset.store
            and cached[1] is dataset.ws
        ):
            return cached[2]
        if cached is not None:
            cached[2].close()
        packer = BatchPacker(
            dataset.store,
            dataset.ws,
            self._schema,
            dense_slot=self.dense_slot,
            dense_dim=self.dense_dim,
            bucket=self.pack_bucket,
        )
        self._packer_cache = (dataset.store, dataset.ws, packer)
        return packer

    def _fast_feed_iter(self, dataset, n_batches):
        """Columnar fast path: native pack + device upload in background
        threads, overlapped with the device step (MiniBatchGpuPack async
        pipeline parity, data_feed.h:1418-1542)."""
        store = dataset.store
        packer = self._get_packer(dataset)
        # one compiled program for the whole pass: L_pad frozen from the
        # full batch partition (U_pad/K self-stabilize with headroom)
        packer.freeze_shapes(
            dataset.batch_indices(n_batches),
            n_devices=self._n_pack_devices if self.plan is not None else 0,
            transport=dataset.transport,
        )
        has_meta = store.ins_id_off is not None

        want_ids = has_meta and self.dump_pool is not None

        def prep(idx):
            if self.plan is None:
                db = packer.pack(idx)
                feed = {
                    k: jax.device_put(v) for k, v in db.as_dict().items()
                }
            else:
                db = packer.pack_sharded(idx, self._n_pack_devices)
                feed = {
                    k: put_sharded(self.plan, v) for k, v in db.as_dict().items()
                }
            # ins_id string extraction belongs in the overlapped worker, not
            # between device steps
            ids = [store.ins_id(int(j)) for j in idx] if want_ids else None
            return idx, feed, ids

        def prep_traced(idx):
            # worker-thread span: the chrome trace shows pack/upload
            # overlapping the device step (RecordEvent parity). device_put
            # returns before the H2D transfer lands, so when tracing we
            # block on the feed INSIDE the worker span — the wait stays off
            # the main thread, which is exactly the prefetch worker's job
            if not PROFILER.enabled:
                return prep(idx)
            with PROFILER.record_event("pack+upload", "pack"):
                out = prep(idx)
                jax.block_until_ready(out[1])
                return out

        for idx, feed, ids in prefetch(dataset.batch_indices(n_batches), prep_traced):
            yield self._feed_aux(
                feed,
                cmatch=store.cmatch[idx] if has_meta else None,
                rank=store.rank[idx] if has_meta else None,
                ins_ids=ids,
            )

    def _classic_stepper(
        self, iterator, holder, step_fn, is_async, profile, t_feed, t_disp, t_dev
    ):
        """Per-batch dispatch over a host-packed feed iterator.

        Yields (batch_index, metrics, aux). Keeps a shallow dispatch window
        (max_inflight_steps): deep enough to hide host->device round-trip
        latency behind compute, shallow enough that transfers and
        executions can't pile up on the transport (an unbounded queue
        measured 3x slower end-to-end on a tunneled TPU than a small
        window)."""
        from collections import deque

        max_inflight = config.get_flag("max_inflight_steps")
        inflight: deque = deque()
        it = iter(iterator)
        i = 0
        while True:
            t_feed.start()
            try:
                with PROFILER.record_event("feed_wait", "pass"):
                    feed, aux = next(it)
            except StopIteration:
                return
            finally:
                t_feed.pause()  # idempotent
            if is_async:  # PullDense / PushDense worker loop (B6)
                fresh = self.async_dense.pull_dense()
                if self.plan is not None:
                    fresh = put_replicated(self.plan, fresh)
                else:
                    fresh = jax.device_put(fresh)
                holder["state"] = holder["state"]._replace(params=fresh)
            # chaos seam: a per-batch device failure (OOM, interconnect
            # reset, preempted core) surfaces here as a dispatch exception
            _fault_fire("step.device")
            t_disp.start()
            with PROFILER.record_event("train_step_dispatch", "pass"):
                holder["state"], m = step_fn(holder["state"], feed)
            t_disp.pause()
            if profile:
                t_dev.start()
                with PROFILER.record_event("device_step", "device"):
                    jax.block_until_ready(m["loss"])
                t_dev.pause()
            elif max_inflight:
                inflight.append(m["loss"])
                if len(inflight) > max_inflight:
                    t_dev.start()
                    jax.block_until_ready(inflight.popleft())
                    t_dev.pause()
            yield i, m, aux
            i += 1

    def _get_resident(self, dataset):
        """Pass-scoped ResidentPass cache (same lifetime as the packer:
        rebuilt when the store or working set changes)."""
        c = getattr(self, "_resident_cache", None)
        if c is not None and c[0] is dataset.store and c[1] is dataset.ws:
            return c[2]
        # a rebuild over the SAME store (pass retry, warmup->timed ws swap)
        # can keep the frozen pad-shape cache: the unique-row count of an
        # index block depends only on the store's keys (distinct keys map
        # to distinct rows in ANY pass working set), so re-deriving it per
        # rebuild just re-runs the pad sweep for identical answers
        prev_uniq = (
            dict(c[2]._uniq_cache)
            if c is not None and c[0] is dataset.store
            else None
        )
        # release the PREVIOUS pass's device arrays (and the jitted
        # supersteps whose closures pin them) BEFORE uploading the new
        # pass's set — otherwise both passes' resident arrays coexist in
        # HBM during prepare, doubling peak device memory
        c = None  # the local ref would keep the old arrays alive too
        self._resident_cache = None
        self._sstep_cache = {}
        self._pv_feed_cache = None  # old pass's pv stacks must release too
        rp = ResidentPass(
            dataset.store,
            dataset.ws,
            self._schema,
            dense_slot=self.dense_slot,
            dense_dim=self.dense_dim,
            bucket=self.pack_bucket,
            plan=self.plan,
            transport=dataset.transport,
        )
        if prev_uniq:
            rp._uniq_cache.update(prev_uniq)
        self._resident_cache = (dataset.store, dataset.ws, rp)
        return rp

    def _pv_locked_plan(self, dataset):
        """The pass's PvPlan with the multi-host ghost-batch count folded
        in — THE one source all pv consumers share (gate, prepare, feed),
        so they can never build differently-locksteped plans. The global
        batch-count allreduce runs once per (pvs, n_dev) and is cached;
        every host takes the cache hit at the same call, so collective
        call counts stay symmetric."""
        n_dev = self._n_pack_devices if self.plan is not None else 1
        multi = self.plan is not None and jax.process_count() > 1
        c = getattr(self, "_pv_minb_cache", None)
        if c is not None and c[0] is dataset.pvs and c[1] == n_dev:
            min_b = c[2]
        else:
            min_b = (
                dataset.num_pv_batches(n_devices=n_dev, global_count=True)
                if multi
                else 0
            )
            self._pv_minb_cache = (dataset.pvs, n_dev, min_b)
        return dataset.pv_plan(n_dev, min_batches=min_b), n_dev

    def _pv_resident_prepare(self, dataset):
        """(rp, plan, device feed) for the resident join phase: build the
        PvPlan, freeze the resident pads over ITS batches (ghost repeats
        count keys but add no uniques), and upload the plan's stacked
        idx/rank_offset/ins_weight once per pass."""
        from paddlebox_tpu.train.resident_step import (
            ResidentPvFeed,
            ensure_sharded,
        )

        rp = self._get_resident(dataset)
        plan, n_dev = self._pv_locked_plan(dataset)
        if self.plan is None:
            rp.ensure(plan.idx)
        else:
            ensure_sharded(rp, plan.idx, self._n_pack_devices)
        c = getattr(self, "_pv_feed_cache", None)
        if c is None or c[0] is not plan or c[1] is not rp:
            feed = ResidentPvFeed(plan, mesh_plan=self.plan)
            self._pv_feed_cache = (plan, rp, feed)
        return rp, plan, self._pv_feed_cache[2]

    def _resident_superstep(self, rp, eval_mode, pv_feed=None):
        # keyed cache (not a single slot): a per-pass train -> eval -> train
        # alternation must reuse both compiled scan programs, like the
        # classic path keeps _step and _eval_step_cache alive side by side
        cache = getattr(self, "_sstep_cache", None)
        if cache is None:
            cache = self._sstep_cache = {}
        key = (id(rp), id(pv_feed), eval_mode, rp.L_pad, rp.U_pad, rp.K_pad)
        ss = cache.get(key)
        if ss is None:
            if pv_feed is not None:
                from paddlebox_tpu.train.resident_step import (
                    make_resident_pv_mesh_superstep,
                    make_resident_pv_superstep,
                )

                if self.plan is None:
                    ss = make_resident_pv_superstep(
                        self.model.apply, self.dense_opt, self.cfg, rp,
                        pv_feed, eval_mode=eval_mode,
                    )
                else:
                    ss = make_resident_pv_mesh_superstep(
                        self.model.apply, self.dense_opt, self.cfg, rp,
                        pv_feed, self.plan, eval_mode=eval_mode,
                    )
            elif self.plan is None:
                ss = make_resident_superstep(
                    self.model.apply, self.dense_opt, self.cfg, rp,
                    eval_mode=eval_mode,
                )
            else:
                from paddlebox_tpu.train.resident_step import (
                    make_resident_mesh_superstep,
                )

                ss = make_resident_mesh_superstep(
                    self.model.apply, self.dense_opt, self.cfg, rp,
                    self.plan, eval_mode=eval_mode,
                )
            cache[key] = ss
        return ss

    def _resident_stepper(
        self, dataset, n_batches, holder, eval_mode, profile, t_feed, t_disp, t_dev,
        use_pv: bool = False,
    ):
        """Superstep dispatch: K batches per lax.scan call, index-only feed.

        Yields the same (batch_index, metrics, aux) stream as the classic
        stepper — metrics are lazy scan-axis slices of the stacked chunk
        output, so unconsumed fields never leave the device.

        ``use_pv`` switches to the join-phase tier: batches come from the
        pass's PvPlan (already resident on device), so the per-chunk feed is
        a [K] vector of batch positions; rank_offset/ins_weight ride along
        from the resident stacks."""
        t_feed.start()
        pv_w = None
        with PROFILER.record_event("resident_prepare", "pass"):
            if use_pv:
                rp, plan, pv_feed = self._pv_resident_prepare(dataset)
                n = plan.n_batches
                if n_batches is not None:
                    n = min(n, n_batches)
                blocks = [plan.idx[i] for i in range(n)]
                pv_w = plan.ins_weight
                sstep = self._resident_superstep(rp, eval_mode, pv_feed=pv_feed)
            else:
                rp = self._get_resident(dataset)
                blocks = [
                    np.asarray(b, dtype=np.int32)
                    for b in dataset.batch_indices(n_batches)
                ]
                if self.plan is None:
                    rp.ensure(blocks)
                else:
                    from paddlebox_tpu.train.resident_step import ensure_sharded

                    ensure_sharded(rp, blocks, self._n_pack_devices)
                sstep = self._resident_superstep(rp, eval_mode)
        t_feed.pause()
        # profiling wants per-batch device attribution: drop to one batch
        # per dispatch (the same overlap-for-attribution trade the classic
        # path makes by blocking every step)
        K = 1 if profile else max(1, int(config.get_flag("resident_scan_batches")))
        store = dataset.store
        has_meta = store.ins_id_off is not None
        want_ids = has_meta and self.dump_pool is not None
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        # ins_id string extraction belongs off the dispatch thread (same
        # rule as the prefetch worker in _fast_feed_iter): one background
        # worker resolves a chunk's ids while its superstep runs
        ids_ex = ThreadPoolExecutor(max_workers=1) if want_ids else None
        try:
            inflight: deque = deque()
            i = 0
            for c0 in range(0, len(blocks), K):
                chunk = blocks[c0 : c0 + K]
                ids_fut = (
                    ids_ex.submit(
                        lambda ch: [
                            [store.ins_id(int(r)) for r in idx] for idx in ch
                        ],
                        chunk,
                    )
                    if want_ids
                    else None
                )
                if use_pv:
                    # the batches live on device already — feed POSITIONS
                    idx_dev = jnp.arange(c0, c0 + len(chunk), dtype=jnp.int32)
                elif self.plan is not None:
                    # [K, B_local] -> [K, n_local, b]: record r -> device
                    # r // b, the same ins // b mapping the sharded packer
                    # uses; the scan axis stays whole, devices split (on a
                    # multi-host mesh each process contributes its local
                    # devices' blocks of LOCAL store indices)
                    from paddlebox_tpu.parallel.mesh import put_axis1_blocks

                    idx_dev = put_axis1_blocks(
                        self.plan,
                        np.stack(chunk).reshape(
                            len(chunk), self._n_pack_devices, -1
                        ),
                    )
                else:
                    idx_dev = jnp.asarray(np.stack(chunk))
                _fault_fire("step.device")  # chaos seam (see classic stepper)
                t_disp.start()
                with PROFILER.record_event("superstep_dispatch", "pass"):
                    holder["state"], mstack = sstep(holder["state"], idx_dev)
                t_disp.pause()
                if profile:
                    t_dev.start()
                    with PROFILER.record_event("device_superstep", "device"):
                        jax.block_until_ready(mstack["loss"])
                    t_dev.pause()
                else:
                    inflight.append(mstack["loss"])
                    if len(inflight) > 1:  # double-buffer supersteps
                        t_dev.start()
                        jax.block_until_ready(inflight.popleft())
                        t_dev.pause()
                chunk_ids = ids_fut.result() if ids_fut is not None else None
                for j, idx in enumerate(chunk):
                    m = {k: v[j] for k, v in mstack.items()}
                    aux = {}
                    if has_meta:
                        aux["cmatch"] = store.cmatch[idx]
                        aux["rank"] = store.rank[idx]
                    if pv_w is not None:
                        aux["ins_weight"] = pv_w[c0 + j]
                    if chunk_ids is not None:
                        aux["ins_ids"] = chunk_ids[j]
                    yield i, m, aux
                    i += 1
        finally:
            if ids_ex is not None:
                ids_ex.shutdown(wait=False)

    def _use_resident(self, dataset: BoxPSDataset, use_pv: bool, is_async: bool) -> bool:
        """One predicate for the resident-vs-packer path, shared by
        train_pass and prepare_pass so the warm-start hook can never
        pre-freeze a different feed path than training will take.

        Covers the single-device step, single-host meshes (resident arrays
        replicate across local devices), and multi-host meshes (each
        device carries its host's pass arrays, pads transport-locksteped)
        — for BOTH tiers: flat, and join-phase (use_pv) via the
        pass-deterministic PvPlan, whose feed is batch POSITIONS into
        resident idx/rank_offset/ins_weight stacks (ghost batches
        equalize multi-host counts). A model that takes rank_offset is
        only excluded from the FLAT tier (no rank matrix exists there to
        feed it)."""
        multi_host = self.plan is not None and jax.process_count() > 1
        ok = (
            bool(config.get_flag("enable_resident_feed"))
            and not is_async
            and dataset.store is not None
            and len(dataset.store.u64_values) < (1 << 31)
            and not (multi_host and dataset.transport is None)
        )
        if multi_host and dataset.transport is not None:
            # the per-host inputs (store size, store presence) can differ —
            # a split decision would send the hosts into DIFFERENT lockstep
            # collectives (packer freeze vs resident allreduces) and
            # deadlock. All hosts take the resident tier only unanimously.
            # Calls are uniform across hosts (prepare/train/eval sequence),
            # so the FIFO tag needs no per-call uniqueifier.
            ok = (
                dataset.transport.allreduce_max(0 if ok else 1, "res-gate")
                == 0
            )
        if not ok:
            # cheap gates first: a multi-host join phase must NOT build the
            # min_batches=0 plan here (its _pv_feed_iter needs the
            # min_batches=min_b variant — a different cache key, so this
            # one would be a wasted full pack sweep)
            return False
        if use_pv:
            # the plan (and with it every record's store index) must exist;
            # building it here is free for train_pass, which needs it next.
            # Multi-host: the plan carries the locksteped ghost-batch count
            # (store-backed hosts always have store indices — availability
            # is uniform across hosts)
            return self._pv_locked_plan(dataset)[0] is not None
        return not self.cfg.model_takes_rank_offset

    def prepare_pass(
        self, dataset: BoxPSDataset, n_batches: Optional[int] = None
    ) -> None:
        """Pre-freeze this pass's pad shapes for the given batch partition.

        Optional warm-start hook: calling this (or training a warmup slice
        covering the partition) before a timed/measured train_pass keeps
        shape growth — and the XLA recompile it triggers — out of the
        measured region. Covers both the resident path (L_pad/U_pad) and
        the columnar packer (freeze_shapes).

        Records its own wall time as ``last_prepare_s`` (bench sub-field:
        the pass-prepare sweep must stay off the critical path — one
        native counter sweep + one allreduce, data_set.cc:2069-2135)."""
        import time as _time

        t0 = _time.perf_counter()
        try:
            self._prepare_pass_inner(dataset, n_batches)
        finally:
            self.last_prepare_s = _time.perf_counter() - t0

    def _prepare_pass_inner(
        self, dataset: BoxPSDataset, n_batches: Optional[int] = None
    ) -> None:
        self._schema = dataset.schema
        if dataset.store is None or dataset.ws is None:
            return
        use_pv = dataset.pv_merged and dataset.current_phase == 1
        is_async = self.cfg.dense_sync_mode == "async" and not self._eval_active
        if use_pv:
            if self._use_resident(dataset, use_pv, is_async):
                self._pv_resident_prepare(dataset)
            # host-packed pv pads freeze at feed time (plan freeze_shapes
            # or, records-only, the _pv_lockstep sweep)
            return
        if self._use_resident(dataset, use_pv, is_async):
            rp = self._get_resident(dataset)
            blocks = (
                np.asarray(b, dtype=np.int32)
                for b in dataset.batch_indices(n_batches)
            )
            if self.plan is None:
                rp.ensure(blocks)
            else:
                from paddlebox_tpu.train.resident_step import ensure_sharded

                ensure_sharded(rp, blocks, self._n_pack_devices)
        else:
            self._get_packer(dataset).freeze_shapes(
                dataset.batch_indices(n_batches),
                n_devices=self._n_pack_devices if self.plan is not None else 0,
                transport=dataset.transport,
            )

    def train_pass(
        self,
        dataset: BoxPSDataset,
        n_batches: Optional[int] = None,
        on_batch: Optional[Callable[[int, Dict], None]] = None,
        profile: bool = False,
    ) -> Dict[str, float]:
        """Train every minibatch of the current pass; returns pass metrics.

        Call between dataset.begin_pass() and dataset.end_pass(...). Dense
        params/opt state carry over to the next pass; the trained sparse
        table is available via trained_table() for end_pass writeback.

        ``profile=True`` (TrainFilesWithProfiler parity, boxps_worker.cc:
        525-620) adds a per-stage wall-clock breakdown under
        ``out["profile"]``: feed_wait (pack+upload not hidden by overlap),
        step_dispatch (host->XLA handoff), device_step (synchronous device
        execution — profiling blocks per batch, so overlap is sacrificed
        for attribution), host_metrics (registry/dump/callbacks).
        """
        if dataset.device_table is None:
            raise RuntimeError("dataset.begin_pass() first")
        self._schema = dataset.schema
        # the ws OBJECT is the cache key (an id() could be recycled across
        # passes and silently serve the previous pass's state)
        state = self._make_state(dataset.device_table, ws_key=dataset.ws)
        losses = []
        # join phase serves pv-merged batches with rank_offset + ghost
        # weights; update phase serves flat batches (EnablePvMerge branch,
        # data_feed.cc:2165-2198)
        use_pv = dataset.pv_merged and dataset.current_phase == 1
        eval_mode = self._eval_active
        is_async = self.cfg.dense_sync_mode == "async" and not eval_mode
        # resident fast path: pass data lives in device HBM, feeds are
        # index-only, K steps per dispatch (train/resident_step.py)
        use_resident = self._use_resident(dataset, use_pv, is_async)
        iterator = None
        if use_resident:
            step_fn = None
        elif use_pv:
            iterator = self._pv_feed_iter(dataset, n_batches)
            step_fn = self._eval_step() if eval_mode else self._step
        elif dataset.store is not None:
            iterator = self._fast_feed_iter(dataset, n_batches)
            step_fn = self._eval_step() if eval_mode else self._step
        else:
            iterator = self._slow_feed_iter(dataset, n_batches)
            step_fn = self._eval_step() if eval_mode else self._step
        # AUC buckets accumulate in device state across train_pass calls
        # within one pass (warmup epochs, join/update phases, sequential
        # slot-shuffle evals); snapshot them so THIS call's metrics are a
        # bucket delta, not the running total
        auc_pos0 = self._host_np(state.auc.pos).copy()
        auc_neg0 = self._host_np(state.auc.neg).copy()
        if self.plan is not None and jax.process_count() > 1:
            if dataset.store is None:
                raise RuntimeError(
                    "multi-host mesh training needs the columnar-store fast "
                    "path (its pad shapes are transport-locksteped); enable "
                    "the native parser so dataset.store is built"
                )
            tp = dataset.transport
            if tp is not None and tp.rank != jax.process_index():
                # row placement puts process i's block at shard i while the
                # working set assigns ownership by transport rank — if the
                # two disagree, every pull silently reads the wrong host's
                # slice
                raise RuntimeError(
                    f"transport rank {tp.rank} != jax process index "
                    f"{jax.process_index()} — order the transport endpoint "
                    "list by jax process id"
                )
            omap = getattr(dataset, "ownership", None)
            if tp is not None and omap is not None and not omap.is_live(tp.rank):
                # after an elastic shrink the ownership map is the source of
                # truth for which ranks may train; a rank outside the live
                # set would pull shard ranges nobody routes to it
                raise RuntimeError(
                    f"transport rank {tp.rank} is not in the live set of "
                    f"ownership epoch {omap.epoch} "
                    f"(live={list(omap.live_ranks)}) — this process was "
                    "voted out of the membership and must not train"
                )
        from paddlebox_tpu.utils.timer import Timer

        t_feed, t_disp, t_dev, t_host = Timer(), Timer(), Timer(), Timer()
        skip_flags: list = []

        # the stepper generators mutate holder["state"] as they dispatch;
        # the consumer loop below is shared between the classic per-batch
        # path and the resident scan path so host-side semantics (registry,
        # dumps, NaN containment, callbacks) can never diverge
        holder = {"state": state}
        if use_resident:
            stepper = self._resident_stepper(
                dataset, n_batches, holder, eval_mode, profile,
                t_feed, t_disp, t_dev, use_pv=use_pv,
            )
        else:
            stepper = self._classic_stepper(
                iterator, holder, step_fn, is_async, profile,
                t_feed, t_disp, t_dev,
            )

        try:
            for i, m, aux in stepper:
                self._consume_batch(
                    i, m, aux, dataset, is_async, on_batch, losses,
                    skip_flags, t_host,
                )
        except BaseException:
            # the cached pre-pass state was donated into this pass's steps;
            # re-point at the last returned state so a retry (or
            # revert+retrain) doesn't touch deleted buffers. If the FAILING
            # call itself consumed that state (XLA runtime error after
            # donation), drop the cache so the retry rebuilds from the
            # dataset's pass-open table instead of crashing on dead arrays
            st = holder["state"]
            alive = True
            try:
                alive = not st.table.is_deleted()
            except AttributeError:
                pass  # host-side array: always alive
            self._state = st if alive else None
            raise
        state = holder["state"]
        # persist dense side for the next pass; state.table stays for writeback
        if eval_mode:
            # values are bit-identical, but the OLD buffers were donated into
            # the eval step — re-point at the returned state (skipping the
            # kstep pass-end sync, whose pmean would perturb bits)
            if self.plan is not None and self.cfg.dense_sync_mode == "kstep":
                self.params = jax.tree.map(lambda x: x[0], state.params)
                self.opt_state = jax.tree.map(lambda x: x[0], state.opt_state)
            else:
                self.params = state.params
                self.opt_state = state.opt_state
        elif is_async:
            # the host table owns the dense params; snapshot its latest view
            self.params = jax.device_put(self.async_dense.pull_dense())
            self.opt_state = state.opt_state  # untouched in async mode
        elif self.plan is not None and self.cfg.dense_sync_mode == "kstep":
            # pass-end SyncParam (boxps_worker.cc:459-461), then store the
            # synced params un-stacked; momentum stays device-0's (the
            # reference likewise syncs only the fused param buffer)
            state = kstep_sync_params(state, self.plan)
            self.params = jax.tree.map(lambda x: x[0], state.params)
            self.opt_state = jax.tree.map(lambda x: x[0], state.opt_state)
        else:
            self.params = state.params
            self.opt_state = state.opt_state
        self._state = state
        if self.dump_pool is not None and self.dump_params_at_end:
            # DumpParam parity (device_worker.cc:131-133): dense params once
            # at pass end, one line per leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(self.params)[0]:
                name = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
                dump_param(self.dump_pool, name, np.asarray(leaf))
        from paddlebox_tpu.metrics.auc import AucState

        cum = AucState(
            pos=self._host_np(state.auc.pos), neg=self._host_np(state.auc.neg)
        )
        delta = AucState(pos=cum.pos - auc_pos0, neg=cum.neg - auc_neg0)
        out = auc_compute(delta)
        cum_out = auc_compute(cum)
        out["auc_cumulative"] = cum_out["auc"]
        # saturation is a property of the CUMULATIVE buckets — the delta is
        # small by construction and would always read unsaturated
        out["saturated"] = cum_out["saturated"]
        if losses and skip_flags:
            lv = jnp.stack(losses)
            bad = jnp.stack(skip_flags) > 0
            kept = jnp.maximum(jnp.sum(~bad), 1)
            out["loss"] = float(jnp.sum(jnp.where(bad, 0.0, lv)) / kept)
            out["nan_batches"] = float(jnp.sum(bad))
        else:
            out["loss"] = float(jnp.mean(jnp.stack(losses))) if losses else float("nan")
            out["nan_batches"] = 0.0
        out["batches"] = float(len(losses))
        if not eval_mode:
            # monitor parity: training-lifecycle counters (an eval pass
            # trains nothing, so it bumps nothing). ins_num counts REAL
            # instances (AUC-masked: no ghosts, no skipped batches);
            # samples_processed is device throughput incl. wraparound pads.
            from paddlebox_tpu.utils.monitor import STAT_ADD

            STAT_ADD("train_batches", len(losses))
            STAT_ADD("train_samples_processed", len(losses) * self.cfg.batch_size)
            STAT_ADD("train_ins_num", out.get("ins_num", 0))
            STAT_ADD("nan_skipped_batches", out["nan_batches"])
        if profile:
            out["profile"] = {
                "feed_wait_s": round(t_feed.elapsed_sec(), 4),
                "step_dispatch_s": round(t_disp.elapsed_sec(), 4),
                "device_step_s": round(t_dev.elapsed_sec(), 4),
                "host_metrics_s": round(t_host.elapsed_sec(), 4),
            }
        return out

    def _consume_batch(
        self, i, m, aux, dataset, is_async, on_batch, losses, skip_flags, t_host
    ) -> None:
        """Host-side per-batch consumers, shared by both steppers."""
        t_host.start()
        if "nan_skipped" in m:  # lazy device array: no per-batch sync
            skip_flags.append(m["nan_skipped"])
        # containment must extend to every host-side consumer: a skipped
        # batch's NaN preds/grads reach neither the async dense table
        # nor the registry/dumps. The int() sync only happens when such
        # a consumer exists (those paths already sync per batch).
        skipped_now = 0
        if "nan_skipped" in m and (
            is_async or self.metric_registry is not None or self.dump_pool is not None
        ):
            skipped_now = int(m["nan_skipped"])
        if is_async and not skipped_now:
            self.async_dense.push_dense(jax.tree.map(np.asarray, m["gparams"]))
        if self.metric_registry is not None and not skipped_now:
            # per-batch registry feed with phase + logkey-derived vars
            # (AddAucMonitor parity, boxps_worker.cc:408-418)
            outputs = dict(m)
            outputs.update(aux)
            self.metric_registry.add_all(outputs, phase=dataset.current_phase)
        if self.dump_pool is not None and not skipped_now:
            self._dump_batch(i, m, aux)
        if on_batch is not None:
            on_batch(i, m)
        losses.append(m["loss"])
        t_host.pause()

    def _dump_batch(self, step_i: int, m: Dict, aux: Dict) -> None:
        """Per-batch field dump (DeviceWorker::DumpField parity,
        device_worker.cc:98-133; sampling modes device_worker.h:218-219)."""
        if not self.dump_pool._started:
            self.dump_pool.start()
        fields = {}
        n_ins = None
        for name in self.dump_fields_list:
            if name not in m:
                continue
            arr = np.asarray(m[name])
            if arr.ndim == 0:
                continue  # scalars (loss, step) have no per-instance rows
            flat = arr.reshape(-1, *arr.shape[2:]) if arr.ndim > 1 else arr
            fields[name] = flat
            n_ins = len(flat) if n_ins is None else min(n_ins, len(flat))
        if not fields or not n_ins:
            return
        ins_ids = aux.get("ins_ids")
        if ins_ids is None or len(ins_ids) != n_ins:
            # no ins-id metadata parsed: fall back to batch-ordinal ids
            ins_ids = [f"b{step_i}:{j}" for j in range(n_ins)]
        dump_fields(
            self.dump_pool,
            ins_ids,
            {k: v[:n_ins] for k, v in fields.items()},
            step=step_i,
            dump_mode=self.dump_mode,
            dump_interval=self.dump_interval,
        )

    def trained_table(self) -> np.ndarray:
        """The pass's trained table for writeback: the full array
        single-host, THIS host's shard block on a multi-process mesh
        (exactly what DistributedWorkingSet.writeback consumes — trained
        rows never cross hosts, EndPass parity box_wrapper.cc:627)."""
        if self._state is None:
            raise RuntimeError("no trained pass")
        if self.plan is not None and jax.process_count() > 1:
            return local_slice(self.plan, self._state.table)
        return np.asarray(self._state.table)

    def handoff_table(self, dataset: BoxPSDataset) -> None:
        """Carry this trainer's trained table into ANOTHER trainer's
        train_pass over the same working set.

        The reference's join and update phases push into one live PS table
        (phase machinery box_wrapper.h:620-622; the dataset is trained twice
        per pass, test_paddlebox_datafeed.py:103-119). Here each CTRTrainer
        binds one step config, so a two-phase pass uses two trainers — the
        join trainer must hand its sparse updates to the update trainer
        explicitly, else phase 2 silently restarts from the pass-open table:

            join_tr.train_pass(ds); join_tr.handoff_table(ds)
            upd_tr.train_pass(ds);  ds.end_pass(upd_tr.trained_table())

        Single-process the handoff stays ON DEVICE (no D2H/H2D round trip
        between phases); only the multi-host path goes through host memory
        (its writeback layout is per-host anyway).
        """
        if self.plan is not None and jax.process_count() > 1:
            t = self.trained_table()
        else:
            if self._state is None:
                raise RuntimeError("no trained pass")
            t = self._state.table
        if t.ndim == 2:  # single-device flat layout -> ws shard layout
            t = t.reshape(-1, dataset.ws.capacity, t.shape[-1])
        dataset.device_table = t

    def trained_table_device(self):
        """The live trained DEVICE table (no transfer): hand this to
        ``end_pass`` to opt into the device-carried boundary
        (table/carrier.py) — the next pass's finalize then splices
        surviving rows on device and fetches only the departing slice.
        Multi-host: the global sharded array; end_pass builds a per-host
        MultiHostCarrier over its addressable shard blocks (the decision
        is locksteped over the transport), so every node keeps its HBM
        cache warm across the boundary (EndPass box_wrapper.cc:627-651)."""
        if self._state is None:
            raise RuntimeError("no trained pass")
        return self._state.table
