"""StreamSupervisor: crash-safe tail-follow ingestion + micro-pass publish.

The paper's unit of progress is the day/hour pass over a fixed file list
(PAPER.md); real CTR serving wants event→served freshness in minutes.
This module turns the pass machinery into a streaming plane:

- :class:`DirectoryTailer` tail-follows an append-only dataset directory:
  per-file byte offset + incremental CRC32 over the bytes it has consumed,
  only COMPLETE lines are ever handed out (an incomplete last line of a
  still-appending file is held back for the next poll, never quarantined
  as a bad record), and the consumed-prefix CRC proves on restart that
  nobody rewrote history under the cursor.

- :class:`StreamSupervisor` cuts micro-passes on a TIME budget
  (``stream_micro_pass_s``) instead of a file list and drives each cut
  through the existing :class:`~paddlebox_tpu.train.supervisor.
  PassSupervisor` machinery — retry/rollback, quarantine admission,
  coordinated verdicts, and the elastic re-anchor path all apply
  unchanged. Each cut publishes a delta through the normal
  watermark/lineage path; the watermark additionally carries
  ``{"stream": {"cut_seq", "oldest_unix", "records"}}`` so followers can
  sample the end-to-end ``serve.freshness_s`` histogram at commit.

Durability (the robustness tentpole) is a two-phase durable cursor under
the checkpoint root, written via ``atomic_write``:

    stream_cursor.json      {"cut_seq", "files": {rel: {offset, crc32}},
                             "pending": null | {...}, "published": {...}}
    stream_spool/cut-NNNNNN.txt   the exact records of one cut, durable
                                  BEFORE training starts

A cut is: (1) spool the polled records, (2) write the cursor with a
``pending`` intent naming the spool (size+CRC pinned) and the post-read
file positions, (3) train+publish the spool through ``run_pass``, (4)
commit the cursor (pending adopted). Recovery after a crash is
exactly-once by construction: a pending whose cut_seq the published
watermark already carries is finalized WITHOUT retraining (no
double-count); a pending that never published replays the SAME durable
spool (no loss, bitwise-identical to the uninterrupted run); a torn
intent is discarded and the committed positions re-read the same bytes.

Compaction: every ``stream_compact_every`` micro-deltas the supervisor
calls :meth:`CheckpointManager.compact`, folding base+delta-0001..N into
one full ``compact-NNNN`` snapshot (bitwise-equal by sequential replay)
so follower catch-up stays O(hours) not O(minutes-since-base).

Backlog degrades gracefully: when a cut overruns its budget the window
stretches (doubling, capped at ``stream_backlog_max_stretch``×budget,
counted under ``stream.backlog_stretches``) and shrinks back once cuts
run under half budget — cadence bends, the stream never crashes.

Fault sites (utils/faultinject): ``stream.tail_read`` fires before each
file's new byte range is consumed; ``stream.cut_publish`` fires at the
two cut crash windows (intent durable / published but cursor stale);
``ckpt.compact`` lives in checkpoint.py.
"""

from __future__ import annotations

import fnmatch
import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from paddlebox_tpu import config
from paddlebox_tpu.table.sparse_table import HostSparseTable
from paddlebox_tpu.train.checkpoint import MembershipEpochError, _file_crc32
from paddlebox_tpu.utils.faultinject import fire as _fault_fire
from paddlebox_tpu.utils.fs import atomic_write
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE

logger = logging.getLogger(__name__)

STREAM_CURSOR_NAME = "stream_cursor.json"
SPOOL_DIR_NAME = "stream_spool"


class StreamLineageError(RuntimeError):
    """The append-only contract of the streamed directory was violated.

    The ingest cursor records a CRC32 over every byte it has consumed; on
    resume the tailer re-hashes those prefixes. A mismatch means a file
    was rewritten or truncated under the cursor — the records already
    trained on no longer exist as recorded, so "resume from the cursor"
    has no meaning. Refusing loudly beats silently re-training rewritten
    history as if it were the original.
    """


def _incremental_crc(path: str, length: int, chunk: int = 1 << 20) -> int:
    """CRC32 over the first ``length`` bytes of ``path``."""
    crc = 0
    remaining = length
    with open(path, "rb") as f:
        while remaining > 0:
            buf = f.read(min(chunk, remaining))
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
            remaining -= len(buf)
    return crc


class DirectoryTailer:
    """Tail-follow an append-only directory of line-oriented record files.

    ``poll()`` scans for files matching ``pattern`` (sorted by name, so
    consumption order is deterministic), reads each file's bytes past its
    recorded offset, and returns the COMPLETE lines found. The bytes after
    the last newline of a still-growing file are the partial-tail hazard:
    they are a record some writer has not finished flushing, so the offset
    never advances past them and they are re-read (whole) on a later poll
    — never handed to the pass loader as a torn record.

    ``positions`` maps relative filename → {"offset", "crc32"} where the
    CRC is incremental over exactly the consumed bytes; it is the
    in-memory half of the durable stream cursor. ``resume(positions)``
    installs a cursor and re-hashes every consumed prefix, raising
    :class:`StreamLineageError` on an append-only violation.

    Records are stamped with the wall-clock of the PREVIOUS poll: a record
    discovered now was absent then, so it was appended no earlier — the
    stamp is a floor on its append time and the freshness SLO computed
    from it overestimates by at most one poll interval (conservative).
    """

    def __init__(self, dirpath: str, pattern: str = "*", wall=time.time):
        self.dirpath = dirpath
        self.pattern = pattern
        self.wall = wall
        self.positions: Dict[str, Dict[str, int]] = {}
        self._prev_poll_unix = float(wall())

    def resume(self, positions: Dict[str, Dict[str, int]]) -> None:
        """Install a durable cursor and verify the consumed prefixes."""
        for rel, pos in positions.items():
            path = os.path.join(self.dirpath, rel)
            off = int(pos["offset"])
            if off == 0:
                continue
            if not os.path.exists(path):
                raise StreamLineageError(
                    f"stream cursor names {rel!r} at offset {off} but the "
                    "file is gone — the streamed directory is append-only"
                )
            if os.path.getsize(path) < off:
                raise StreamLineageError(
                    f"{rel!r} shrank below the consumed offset {off} — "
                    "the streamed directory is append-only"
                )
            if _incremental_crc(path, off) != int(pos["crc32"]):
                raise StreamLineageError(
                    f"consumed prefix of {rel!r} (first {off} bytes) no "
                    "longer matches the cursor CRC — history was rewritten "
                    "under the stream cursor"
                )
        self.positions = {
            rel: {"offset": int(p["offset"]), "crc32": int(p["crc32"])}
            for rel, p in positions.items()
        }

    def _list_files(self) -> List[str]:
        try:
            names = os.listdir(self.dirpath)
        # a not-yet-created stream dir is an empty stream, not an error
        # pbox-lint: disable=EXC007
        except OSError:
            return []
        return sorted(n for n in fnmatch.filter(names, self.pattern)
                      if not n.endswith(".tmp"))

    def poll(self) -> Tuple[List[str], float]:
        """One scan; returns (new complete lines, conservative stamp).

        A file whose read fails (I/O error or injected ``stream.tail_read``
        fault) is skipped WITHOUT advancing its position — the next poll
        re-reads the same byte range, so a transient read failure costs
        latency, never records (counted under ``stream.tail_read_errors``).
        """
        stamp = self._prev_poll_unix
        self._prev_poll_unix = float(self.wall())
        lines: List[str] = []
        for rel in self._list_files():
            path = os.path.join(self.dirpath, rel)
            pos = self.positions.setdefault(rel, {"offset": 0, "crc32": 0})
            try:
                _fault_fire("stream.tail_read")
                with open(path, "rb") as f:
                    f.seek(pos["offset"])
                    buf = f.read()
            except OSError as e:  # includes InjectedFault
                STAT_ADD("stream.tail_read_errors")
                logger.warning(
                    "stream: tail read of %s failed (position held, will "
                    "re-read): %s", rel, e,
                )
                continue
            if not buf:
                continue
            # partial-tail holdback: only bytes up to (and including) the
            # last newline are consumed; a writer mid-flush keeps its torn
            # record private until it finishes the line
            cut = buf.rfind(b"\n")
            if cut < 0:
                continue
            consumed = buf[: cut + 1]
            # undecodable bytes inside a COMPLETE line are a bad record,
            # not a torn one: keep the line (with replacement chars) so the
            # pass loader's quarantine path judges it, same as file input
            lines.extend(consumed.decode("utf-8", errors="replace").splitlines())
            pos["offset"] += len(consumed)
            pos["crc32"] = zlib.crc32(consumed, pos["crc32"])
            STAT_ADD("stream.bytes_consumed", len(consumed))
        if lines:
            STAT_ADD("stream.records_polled", len(lines))
        return lines, stamp

    def snapshot_positions(self) -> Dict[str, Dict[str, int]]:
        return {rel: dict(p) for rel, p in self.positions.items()}


# ---- micro-pass boundary protocol ----------------------------------------
#
# Coordinated streaming ranks fence each cut with the SAME verdict
# vocabulary every other boundary uses (ctl:verdict:<key>@e<N>, DST009-
# covered via EpochCoordinator.exchange_verdict): a cut round before
# training — every rank agrees cut_seq N is happening — and a confirm
# round after publish — every rank's delta N is durable. Single-rank
# streams (coord is None) skip both; their exactly-once story is carried
# entirely by the durable cursor.


def stream_cut_round(coord, cut_seq: int, ok: bool = True, detail: str = ""):
    """Epoch-fenced agreement that micro-pass ``cut_seq`` is being cut."""
    return coord.exchange_verdict(f"stream-cut:{cut_seq}", ok, detail)


def stream_confirm_round(coord, cut_seq: int, ok: bool = True, detail: str = ""):
    """Epoch-fenced confirmation that ``cut_seq``'s publish is durable."""
    return coord.exchange_verdict(f"stream-confirm:{cut_seq}", ok, detail)


class StreamSupervisor:
    """Drive a PassSupervisor from a tailed append-only directory.

    One instance owns the stream cursor under ``supervisor.checkpoint``'s
    root. Constructing it runs crash recovery (see module docstring): a
    pending cut left by a crash is either finalized (already published —
    no retrain) or replayed from its durable spool (never published — no
    loss), bitwise-identical to the run that never crashed.

    ``step()`` is the deterministic unit (one poll, one cut if records
    arrived) — tests and soaks drive it directly; ``run(stop)`` is the
    production loop that cuts on the ``stream_micro_pass_s`` time budget
    with graceful backlog stretching.
    """

    def __init__(
        self,
        supervisor,
        stream_dir: str,
        date: str,
        pattern: str = "*",
        micro_pass_s: Optional[float] = None,
        poll_interval_s: Optional[float] = None,
        compact_every: Optional[int] = None,
        clock=time.monotonic,
        wall=time.time,
    ):
        if supervisor.checkpoint is None:
            raise ValueError(
                "StreamSupervisor needs a checkpointed PassSupervisor — "
                "the durable stream cursor lives under the checkpoint root"
            )
        self.sup = supervisor
        self.mgr = supervisor.checkpoint
        self.date = date
        self.clock = clock
        self.micro_pass_s = (
            float(config.get_flag("stream_micro_pass_s"))
            if micro_pass_s is None else float(micro_pass_s)
        )
        self.poll_interval_s = (
            float(config.get_flag("stream_poll_interval_s"))
            if poll_interval_s is None else float(poll_interval_s)
        )
        self.compact_every = (
            int(config.get_flag("stream_compact_every"))
            if compact_every is None else int(compact_every)
        )
        self.tailer = DirectoryTailer(stream_dir, pattern=pattern, wall=wall)
        self.cut_seq = 0
        self._stretch = 1.0
        self._recover()

    # ---- durable cursor --------------------------------------------------

    def _cursor_path(self) -> str:
        return os.path.join(self.mgr.root, STREAM_CURSOR_NAME)

    def _spool_rel(self, cut_seq: int) -> str:
        return os.path.join(SPOOL_DIR_NAME, f"cut-{cut_seq:06d}.txt")

    def read_cursor(self) -> Optional[Dict[str, Any]]:
        path = self._cursor_path()
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        # atomic_write publish: absent-or-torn reads as None, never garbage
        # pbox-lint: disable=EXC007
        except (OSError, ValueError):
            return None

    def _write_cursor(
        self, pending: Optional[Dict[str, Any]] = None
    ) -> None:
        cur = {
            "version": 1,
            "cut_seq": self.cut_seq,
            # always the COMMITTED positions: a pending cut's post-read
            # positions live inside the pending intent until it finalizes
            "files": self._committed_files,
            "pending": pending,
            "published": self._published_pos(),
        }
        with atomic_write(self._cursor_path()) as f:
            json.dump(cur, f)

    def _published_pos(self) -> Optional[Dict[str, Any]]:
        cur = self.mgr.cursor()
        if cur is None:
            return None
        return {"date": cur["date"], "delta_idx": int(cur["delta_idx"])}

    # ---- recovery --------------------------------------------------------

    def _recover(self) -> None:
        cur = self.read_cursor()
        if cur is None:
            self._committed_files: Dict[str, Any] = {}
            return
        self.cut_seq = int(cur.get("cut_seq", 0))
        self._committed_files = dict(cur.get("files") or {})
        # committed positions first: a discarded pending falls back to
        # them, and resume() proves nobody rewrote the consumed prefixes
        self.tailer.resume(self._committed_files)
        pending = cur.get("pending")
        if pending is None:
            return
        seq = int(pending["cut_seq"])
        spool = os.path.join(self.mgr.root, pending["spool"])
        spool_ok = (
            os.path.exists(spool)
            and _file_crc32(spool) == int(pending["spool_crc"])
        )
        if not spool_ok:
            # torn intent: the spool never became durable, so the cut never
            # logically happened — committed positions still point BEFORE
            # these records and the next poll re-reads the same bytes
            STAT_ADD("stream.pending_discarded")
            logger.warning(
                "stream: discarding torn pending cut %d (spool missing or "
                "CRC mismatch) — records will be re-read from the "
                "committed cursor", seq,
            )
            self._write_cursor(pending=None)
            return
        wm = self.mgr.read_watermark() or {}
        published_seq = int((wm.get("stream") or {}).get("cut_seq", 0))
        if published_seq >= seq:
            # the crash hit AFTER publish but before the cursor commit:
            # the records are already in the published chain — finalize
            # without retraining (zero duplicates)
            STAT_ADD("stream.replays_skipped")
            logger.info(
                "stream: pending cut %d already published (watermark at "
                "cut %d) — finalizing without retrain", seq, published_seq,
            )
            self._finalize(seq, pending["files"])
            return
        # the crash hit after the intent but before publish: replay the
        # SAME durable spool through the pass machinery (zero loss, and
        # bitwise-identical input to the run that never crashed)
        STAT_ADD("stream.replays")
        logger.info("stream: replaying pending cut %d from %s", seq, spool)
        self._train_publish(
            seq, spool,
            oldest_unix=pending.get("oldest_unix"),
            records=int(pending.get("records", 0)),
        )
        self._finalize(seq, pending["files"])

    def _finalize(self, seq: int, files: Dict[str, Any]) -> None:
        self.cut_seq = seq
        self._committed_files = dict(files)
        self.tailer.resume(self._committed_files)
        self._write_cursor(pending=None)
        self._gc_spools()

    # ---- cutting ---------------------------------------------------------

    def step(self) -> Optional[int]:
        """One poll; cut a micro-pass when complete records arrived.

        Returns the committed cut_seq, or None when the poll found
        nothing. This is the deterministic unit: a soak that drives
        ``step()`` per appended chunk is bitwise-comparable across
        kill/restart, independent of wall-clock cadence.
        """
        records, stamp = self.tailer.poll()
        if not records:
            return None
        return self._cut(records, stamp)

    def _cut(self, records: List[str], oldest_unix: float) -> int:
        seq = self.cut_seq + 1
        spool_rel = self._spool_rel(seq)
        spool = os.path.join(self.mgr.root, spool_rel)
        with atomic_write(spool) as f:
            f.write("\n".join(records) + "\n")
        pending = {
            "cut_seq": seq,
            "spool": spool_rel,
            "spool_crc": _file_crc32(spool),
            "files": self.tailer.snapshot_positions(),
            "oldest_unix": float(oldest_unix),
            "records": len(records),
        }
        self._write_cursor(pending=pending)
        _fault_fire("stream.cut_publish")  # window: intent durable, untrained
        self._train_publish(
            seq, spool, oldest_unix=oldest_unix, records=len(records)
        )
        _fault_fire("stream.cut_publish")  # window: published, cursor stale
        self._finalize(seq, pending["files"])
        STAT_ADD("stream.cuts")
        return seq

    def _train_publish(
        self, seq: int, spool: str, oldest_unix, records: int
    ) -> None:
        # stamped BEFORE the save so the watermark of this publish carries
        # the ingest floor of its oldest record (follower freshness SLO)
        self.mgr.stream_meta = {
            "cut_seq": seq,
            "oldest_unix": None if oldest_unix is None else float(oldest_unix),
            "records": int(records),
        }
        coord = self.sup.coord
        if coord is not None:
            ok, detail = stream_cut_round(coord, seq)
            if not ok:
                raise RuntimeError(
                    f"stream cut {seq} aborted by a peer: {detail}"
                )
        cur = self.mgr.cursor()
        # first publish of the stream date anchors a base; after that each
        # cut is a minute-level delta. A forced mid-stream re-anchor
        # (elastic epoch flip) is the supervisor's _force_base /
        # MembershipEpochError path — run_pass pauses the cadence, saves a
        # fresh base under the new epoch, and the stream resumes from the
        # cursor with the SLO bent, not broken.
        mode = "base" if cur is None or cur["date"] != self.date else "delta"
        t0 = self.clock()
        self.sup.run_pass([spool], date=self.date, save=mode)
        STAT_OBSERVE("stream.cut_train_s", self.clock() - t0)
        if coord is not None:
            stream_confirm_round(coord, seq)
        self.maybe_compact()

    # ---- compaction ------------------------------------------------------

    def maybe_compact(self) -> Optional[str]:
        """Fold the chain when ``stream_compact_every`` deltas accumulated."""
        if self.compact_every <= 1:
            return None
        cur = self.mgr.cursor()
        if cur is None or cur["date"] != self.date:
            return None
        if int(cur.get("ownership_epoch", 0)) != int(self.mgr.ownership_epoch):
            return None  # mid-flip: the next cut re-anchors first
        behind = int(cur["delta_idx"]) - int(cur.get("compact") or 0)
        if behind < self.compact_every:
            return None
        table = self.sup.table
        scratch = HostSparseTable(
            table.layout, table.opt, n_shards=table.n_shards, seed=0
        )
        try:
            return self.mgr.compact(self.date, scratch)
        except MembershipEpochError:
            # an epoch flip landed between the cursor read and the fold —
            # the compact is deferred to after the re-anchor, exactly like
            # a delta refusing to straddle the flip
            # pbox-lint: disable=EXC007
            STAT_ADD("stream.compact_deferred")
            return None

    # ---- production loop -------------------------------------------------

    def run(
        self,
        stop: threading.Event,
        max_cuts: Optional[int] = None,
        sleep=None,
    ) -> int:
        """Cut micro-passes on the time budget until ``stop`` is set.

        Collects tailed records for ``stream_micro_pass_s`` (polling every
        ``stream_poll_interval_s``), then cuts. A cut that overruns its
        window stretches the next one (doubling, capped at
        ``stream_backlog_max_stretch`` × budget, counted under
        ``stream.backlog_stretches``); windows shrink back once cuts run
        under half budget. Returns the number of cuts made.
        """
        sleep_fn = sleep if sleep is not None else stop.wait
        max_stretch = float(config.get_flag("stream_backlog_max_stretch"))
        cuts = 0
        backlog: List[str] = []
        oldest: Optional[float] = None
        while not stop.is_set():
            window = self.micro_pass_s * self._stretch
            deadline = self.clock() + window
            while self.clock() < deadline and not stop.is_set():
                recs, stamp = self.tailer.poll()
                if recs:
                    backlog.extend(recs)
                    if oldest is None:
                        oldest = stamp
                sleep_fn(
                    max(0.0, min(self.poll_interval_s,
                                 deadline - self.clock()))
                )
            if not backlog:
                continue
            t0 = self.clock()
            self._cut(backlog, oldest if oldest is not None else time.time())
            cut_cost = self.clock() - t0
            backlog, oldest = [], None
            cuts += 1
            if cut_cost > window:
                new = min(self._stretch * 2.0, max_stretch)
                if new > self._stretch:
                    STAT_ADD("stream.backlog_stretches")
                    logger.warning(
                        "stream: cut %d took %.2fs over a %.2fs window — "
                        "stretching cadence x%.1f", self.cut_seq, cut_cost,
                        window, new,
                    )
                self._stretch = new
            elif cut_cost < window / 2.0 and self._stretch > 1.0:
                self._stretch = max(1.0, self._stretch / 2.0)
            if max_cuts is not None and cuts >= max_cuts:
                break
        return cuts

    # ---- housekeeping ----------------------------------------------------

    def _gc_spools(self) -> None:
        """Retire spools older than the previous committed cut (keep one
        back, mirroring the dense-retire discipline)."""
        spool_dir = os.path.join(self.mgr.root, SPOOL_DIR_NAME)
        if not os.path.isdir(spool_dir):
            return
        keep = {f"cut-{s:06d}.txt" for s in (self.cut_seq, self.cut_seq - 1)}
        for name in os.listdir(spool_dir):
            if not name.startswith("cut-") or name in keep:
                continue
            try:
                os.remove(os.path.join(spool_dir, name))
            except OSError:
                # a leaked spool is disk creep, not a correctness problem
                # pbox-lint: disable=EXC007
                STAT_ADD("stream.spool_retire_failures")
