"""Async dense table: host-side background dense optimizer (B6).

Parity with BoxPSAsynDenseTable (boxps_worker.cc:35-237, device_worker.h:
586-617): device workers *pull* the current dense params before each batch
and *push* raw gradients after it; a background host thread drains the grad
queue, merges up to ``merge_limit`` packages (mean), and applies the
reference's fixed Adam-like rule

    mom1 = 0.99 * mom1 + 0.01 * g
    mom2 = 0.9999 * mom2 + 0.0001 * g*g
    p   -= lr * mom1 / (sqrt(mom2) + 1e-8)

(the "magic beta and epsilon" constants, boxps_worker.cc:166-175) with a
per-parameter lr override map (GetLRMap parity, box_wrapper.cc:1234-1241).

TPU shape: params live as a numpy pytree guarded by a rw-lock; ``pull_dense``
returns the current tree (to be fed into a step whose config sets
``dense_sync_mode="async"`` so the device never updates params itself), and
``push_dense`` enqueues the step's gparams. Training proceeds without
waiting on the optimizer — the asynchrony/staleness semantics match the
reference (workers may train on params a few updates old).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np


class AsyncDenseTable:
    """Background-thread dense optimizer with pull/push worker API."""

    def __init__(
        self,
        params: Any,  # pytree of arrays (initial values)
        base_lr: float,
        lr_map: Optional[Dict[str, float]] = None,  # leaf-path -> lr override
        merge_limit: int = 4,
        queue_cap: int = 24,  # PSBufferQueue(8 * 3) parity
    ):
        leaves, self._treedef = jax.tree.flatten(params)
        self._params = [np.array(x, dtype=np.float32) for x in leaves]  # guarded-by: _lock
        self._mom1 = [np.zeros_like(x) for x in self._params]  # guarded-by: _lock
        self._mom2 = [np.zeros_like(x) for x in self._params]  # guarded-by: _lock
        self.base_lr = float(base_lr)
        self.merge_limit = merge_limit
        # leaf lr: lr_map keys match normalized "/"-joined key paths, exact
        # or path-suffix ("mlp/w0" matches key "w0" and key "mlp/w0", never
        # the substring-style accident of "w" matching "w0")
        def norm(kp) -> str:
            parts = []
            for e in kp:
                for attr in ("key", "idx", "name"):
                    if hasattr(e, attr):
                        parts.append(str(getattr(e, attr)))
                        break
                else:
                    parts.append(str(e))
            return "/".join(parts)

        paths = [
            norm(kp) for kp, _ in jax.tree_util.tree_flatten_with_path(params)[0]
        ]

        def leaf_lr(path: str) -> float:
            m = lr_map or {}
            if path in m:  # exact path beats any suffix entry
                return m[path]
            for k, v in m.items():
                if path.endswith("/" + k):
                    return v
            return self.base_lr

        self._leaf_lr = np.array([leaf_lr(p) for p in paths], dtype=np.float32)
        self._lock = threading.Lock()  # guards _params/_mom*/_n_updates
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_cap)
        self._n_updates = 0  # guarded-by: _lock
        self._closed = False
        self._thread = threading.Thread(target=self._update_loop, daemon=True)
        self._thread.start()

    # ---- worker API ------------------------------------------------------

    def pull_dense(self) -> Any:
        """Current param tree (PullDense parity). Cheap copy under lock."""
        with self._lock:
            leaves = [x.copy() for x in self._params]
        return jax.tree.unflatten(self._treedef, leaves)

    def push_dense(self, gparams: Any) -> None:
        """Enqueue one step's dense grads (PushDense parity). Blocks only
        when the queue is full (producer backpressure, like the reference's
        bounded channel)."""
        if self._closed:
            raise RuntimeError("table finalized")
        leaves = jax.tree.leaves(gparams)
        self._queue.put([np.asarray(x, dtype=np.float32) for x in leaves])

    @property
    def n_updates(self) -> int:
        # lock, not a bare read: int reads are atomic under the GIL today,
        # but the lock also ORDERS this against a concurrent _apply so a
        # caller that saw n_updates == k reads params at least that fresh
        with self._lock:
            return self._n_updates

    # ---- background optimizer -------------------------------------------

    def _update_loop(self) -> None:
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if first is None:  # close sentinel
                return
            batch = [first]
            # merge up to merge_limit-1 more waiting packages (AsyncUpdate
            # merge_num = min(queue size + 1, 4))
            while len(batch) < self.merge_limit:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._apply(batch)
                    return
                batch.append(nxt)
            self._apply(batch)

    def _apply(self, batch) -> None:
        inv = 1.0 / len(batch)
        with self._lock:
            for i in range(len(self._params)):
                g = batch[0][i]
                for other in batch[1:]:
                    g = g + other[i]
                if len(batch) > 1:
                    g = g * inv
                m1, m2 = self._mom1[i], self._mom2[i]
                m1 *= 0.99
                m1 += 0.01 * g
                m2 *= 0.9999
                m2 += 0.0001 * g * g
                self._params[i] -= self._leaf_lr[i] * m1 / (np.sqrt(m2) + 1e-8)
            self._n_updates += 1

    # ---- lifecycle -------------------------------------------------------

    def finalize(self) -> Any:
        """Drain the queue, stop the thread, return the final params
        (Finalize copies ps_ back to the root scope)."""
        if not self._closed:
            self._closed = True
            self._queue.put(None)
            self._thread.join()
            # drain anything left after the sentinel raced in
            leftovers = []
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not None:
                    leftovers.append(item)
            for item in leftovers:
                self._apply([item])
        return self.pull_dense()
