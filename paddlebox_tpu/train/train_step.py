"""The jitted train step — the whole per-batch pipeline in one XLA program.

This one function replaces the reference's per-batch op loop
(BoxPSWorker::TrainFiles boxps_worker.cc:420-466: pull_box_sparse →
fused_seqpool_cvm → dense ops → push_box_sparse → dense sync → AUC):

    pull rows → seqpool+CVM → model fwd/bwd → sparse adagrad scatter →
    dense optimizer (+ cross-device psum) → AUC accumulate

Everything is static-shape; the host packer (data/device_pack.py) prepared
row ids / segment ids / padding. On a mesh the same local step runs under
shard_map with the table sharded and dense grads/metrics psum'd — the
single-device path is the degenerate axis_name=None case.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from paddlebox_tpu.metrics.auc import AucState, auc_init, auc_update
from paddlebox_tpu.ops.pull_push import (
    pull_sparse_rows,
    pull_sparse_rows_extended,
    push_sparse_rows,
)
from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm
from paddlebox_tpu.table.optimizers import SparseOptimizerConfig
from paddlebox_tpu.table.value_layout import ValueLayout


class TrainState(NamedTuple):
    table: jnp.ndarray  # [rows, width] pass working-set (flat across shards)
    params: Any  # dense model params
    opt_state: Any  # optax state
    auc: AucState
    step: jnp.ndarray  # int32 scalar


@dataclass(frozen=True)
class TrainStepConfig:
    num_slots: int
    batch_size: int
    layout: ValueLayout
    sparse_opt: SparseOptimizerConfig = SparseOptimizerConfig()
    use_cvm: bool = True
    clk_filter: bool = False
    pull_scale: float = 1.0
    auc_buckets: int = 100_000
    axis_name: Optional[str] = None  # set on a mesh; None = single device
    slot_lr: Optional[tuple] = None  # per-slot lr multipliers, len num_slots
    # join-phase models taking the pv rank matrix get it as a 4th arg:
    # model_apply(params, slot_feats, dense, rank_offset)
    model_takes_rank_offset: bool = False
    # extended pull (pull_box_extended_sparse parity): layout must have
    # expand_embed_dim > 0; the model receives sum-pooled expand embeddings
    # [B, S, E] as its last positional arg and their grads flow back into
    # the table's expand block
    use_expand: bool = False
    # dense sync mode (BoxPSWorker sync_mode_, boxps_worker.cc:239-240):
    #  "step"  - allreduce dense grads every step (default; DP-sync parity)
    #  "kstep" - LocalSGD: local updates, params averaged across the mesh
    #            every param_sync_step steps + at pass end (DenseKStepNode/
    #            ALL parity — the NCCL reduce-scatter + closed SyncDense +
    #            allgather hierarchy collapses into one XLA all-reduce)
    #  "async" - device never updates dense params; gparams are returned in
    #            metrics for a host AsyncDenseTable (B6) pull/push loop
    dense_sync_mode: str = "step"
    param_sync_step: int = 16  # K for "kstep"
    # NaN/Inf containment (check_nan_var_names parity,
    # trainer_desc.proto:43): a batch with a non-finite loss or gradient is
    # SKIPPED in its entirety — no sparse push, no dense update, no AUC —
    # instead of silently poisoning the table; metrics report nan_skipped.
    check_nan: bool = False
    # AdjustInsWeight parity (downpour_worker.cc:271-340): up-weight the
    # LOSS of instances whose nid slot's show count is under threshold —
    # w = max(w, log(e + (T - nid_show)/T * ratio)) — so rarely-shown ads
    # still learn. (nid_slot_index, threshold, ratio); the nid slot is
    # assumed single-feasign like the reference. Only the loss weight
    # changes: show/clk counters keep their unweighted (or pv-ghost 0/1)
    # semantics, exactly as the reference's push records do.
    adjust_ins_weight: Optional[tuple] = None

    def __post_init__(self):
        if self.adjust_ins_weight is not None:
            nid, thr, ratio = self.adjust_ins_weight
            if not (0 <= nid < self.num_slots) or thr <= 0 or ratio < 0:
                raise ValueError(
                    f"adjust_ins_weight=(nid_slot, threshold>0, ratio>=0), "
                    f"got {self.adjust_ins_weight!r} with {self.num_slots} slots"
                )
        if self.dense_sync_mode not in ("step", "kstep", "async"):
            raise ValueError(
                f"dense_sync_mode {self.dense_sync_mode!r} not in "
                "('step', 'kstep', 'async')"
            )
        if self.dense_sync_mode == "kstep" and self.param_sync_step < 1:
            raise ValueError("param_sync_step must be >= 1 for kstep")


def init_train_state(
    table: jnp.ndarray,
    params: Any,
    dense_opt: optax.GradientTransformation,
    auc_buckets: int = 100_000,
) -> TrainState:
    return TrainState(
        table=table,
        params=params,
        opt_state=dense_opt.init(params),
        auc=auc_init(auc_buckets),
        step=jnp.zeros((), jnp.int32),
    )


def local_forward_backward(
    model_apply: Callable,
    cfg: TrainStepConfig,
    params: Any,
    flat: jnp.ndarray,  # [L, PW] pulled records per flat key
    segments: jnp.ndarray,  # [L]
    labels: jnp.ndarray,  # [b]
    dense: Optional[jnp.ndarray],
    ins_weight: Optional[jnp.ndarray] = None,  # [b] 0 masks ghost-padded ins
    rank_offset: Optional[jnp.ndarray] = None,  # [b, 2R+1] join-phase pv matrix
    loss_denom: Optional[jnp.ndarray] = None,  # weighted-loss denominator
    eval_mode: bool = False,  # forward only: grads come back as None
):
    """Shared fwd/bwd body: seqpool+CVM -> model -> BCE, grads wrt (params, flat).

    Used by both the single-device and the mesh-sharded step so the numerics
    can never diverge between them. With ``ins_weight`` the loss is the
    weighted mean, so weight-0 ghosts (pv batch padding) produce exactly zero
    gradient everywhere. ``loss_denom`` overrides the weight-sum denominator —
    the mesh step passes the GLOBAL (psum'd) weight sum so per-device ghost
    imbalance cannot skew sample weighting.
    """

    def loss_fn(p, flat_records):
        if cfg.use_expand:  # trailing expand columns pool separately
            E = cfg.layout.expand_dim
            expand_flat = flat_records[:, -E:]
            flat_records = flat_records[:, :-E]
        slot_feats = fused_seqpool_cvm(
            flat_records,
            segments,
            num_slots=cfg.num_slots,
            batch_size=cfg.batch_size,
            use_cvm=cfg.use_cvm,
            clk_filter=cfg.clk_filter,
        )
        extra = []
        if cfg.model_takes_rank_offset:
            extra.append(rank_offset)
        if cfg.use_expand:
            # sum-pool expand per (slot, ins): [B, S, E] (pad segments drop)
            pooled = jax.ops.segment_sum(
                expand_flat,
                segments,
                num_segments=cfg.num_slots * cfg.batch_size,
            ).reshape(cfg.num_slots, cfg.batch_size, E)
            extra.append(jnp.transpose(pooled, (1, 0, 2)))
        logits = model_apply(p, slot_feats, dense, *extra)
        loss_vec = optax.sigmoid_binary_cross_entropy(logits, labels)
        if ins_weight is not None:
            denom = (
                loss_denom
                if loss_denom is not None
                else jnp.maximum(jnp.sum(ins_weight), 1.0)
            )
            loss = jnp.sum(loss_vec * ins_weight) / denom
        else:
            loss = jnp.mean(loss_vec)
        return loss, jax.nn.sigmoid(logits)

    if eval_mode:
        loss, preds = loss_fn(params, flat)
        return loss, preds, None, None
    (loss, preds), (gparams, gflat) = jax.value_and_grad(
        loss_fn, argnums=(0, 1), has_aux=True
    )(params, flat)
    return loss, preds, gparams, gflat


def scale_and_merge_grads(
    cfg: TrainStepConfig,
    gflat: jnp.ndarray,  # [L, PW]
    segments: jnp.ndarray,  # [L]
    inverse: jnp.ndarray,  # [L] flat key -> merge position
    labels: jnp.ndarray,  # [b]
    num_segments: int,
    grad_div: float = 1.0,
    ins_weight: Optional[jnp.ndarray] = None,  # [b] ghosts -> 0 show/clk
):
    """Shared push-side merge: slot-lr scale, pad mask, per-position sums.

    Returns (merged grads, show counts, clk counts), each [num_segments, ...].
    ``grad_div`` rescales local-mean grads to global-mean on a mesh.
    """
    S, b = cfg.num_slots, cfg.batch_size
    if grad_div != 1.0:
        gflat = gflat / grad_div
    if cfg.slot_lr is not None:
        slot_of_key = jnp.minimum(segments // b, S - 1)
        lr_tab = jnp.asarray(cfg.slot_lr, jnp.float32)
        gflat = gflat * lr_tab[slot_of_key][:, None]
    pad_mask = (segments < S * b).astype(jnp.float32)  # [L] 0 on pad keys
    ins_of_key = segments % b
    # valid = pad mask x instance weight: ghosts add no show/clk
    valid = (
        pad_mask if ins_weight is None else pad_mask * jnp.take(ins_weight, ins_of_key)
    )
    gflat = gflat * pad_mask[:, None]
    # ONE segment reduction for grads + show + clk: scatter passes dominate
    # the push side on TPU, and three width-w scatters cost ~3x one
    # width-(w+2) scatter (PushMergeCopy fuses the same way, box_wrapper.cu)
    ext = jnp.concatenate(
        [gflat, valid[:, None], (jnp.take(labels, ins_of_key) * valid)[:, None]],
        axis=1,
    )
    summed = jax.ops.segment_sum(ext, inverse, num_segments=num_segments)
    return summed[:, :-2], summed[:, -2], summed[:, -1]


def adjusted_loss_weight(
    cfg: TrainStepConfig,
    flat: jnp.ndarray,  # [L, PW(+E)] pulled records (col 0 = show)
    segments: jnp.ndarray,  # [L]
    ins_weight: Optional[jnp.ndarray],  # [b] pv/ghost weights or None
    b: int,
):
    """(loss_weight [b], loss_denom scalar-or-None) for AdjustInsWeight.

    Shared by both step builders: nid_show per instance comes from the nid
    slot's pulled show column (single-feasign slot, downpour_worker.cc:310
    asserts the same); the denominator stays the REAL-instance count so
    up-weighting doesn't silently renormalize away.
    """
    nid, thr, ratio = cfg.adjust_ins_weight
    S = cfg.num_slots
    slot_of_key = segments // b
    ins_of_key = segments % b
    is_nid = (slot_of_key == nid) & (segments < S * b)
    nid_show = jax.ops.segment_max(
        jnp.where(is_nid, flat[:, 0], -jnp.inf), ins_of_key, num_segments=b
    )
    base = ins_weight if ins_weight is not None else jnp.ones((b,), jnp.float32)
    adj = jnp.log(jnp.e + (thr - nid_show) / thr * ratio)
    loss_w = jnp.where(
        (nid_show >= 0) & (nid_show < thr), jnp.maximum(base, adj), base
    )
    # weight-0 ghosts (pv padding carries a REAL ad's nid) must stay
    # exactly zero — up-weighting may never resurrect them
    loss_w = jnp.where(base > 0, loss_w, base)
    denom = (
        jnp.asarray(float(b))
        if ins_weight is None
        else jnp.maximum(jnp.sum(ins_weight), 1.0)
    )
    return loss_w, denom


def make_train_step(
    model_apply: Callable,
    dense_opt: optax.GradientTransformation,
    cfg: TrainStepConfig,
    eval_mode: bool = False,
) -> Callable:
    """Build ``step(state, batch_dict) -> (state, metrics)`` (pure, jittable).

    ``batch_dict`` fields: uniq_rows [U], inverse [L], segments [L],
    labels [B], optional dense [B, Dd]. See data/device_pack.py.

    ``eval_mode`` is the SetTestMode path (box_wrapper.cc:623,
    infer_from_dataset executor.py:1520): forward + metrics only — no
    sparse push, no dense update; table/params/opt_state return
    bit-identical.
    """
    lay, opt = cfg.layout, cfg.sparse_opt
    S, B = cfg.num_slots, cfg.batch_size

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
        uniq_rows = batch["uniq_rows"]
        inverse = batch["inverse"]
        segments = batch["segments"]
        labels = batch["labels"]
        dense = batch.get("dense")
        ins_weight = batch.get("ins_weight")
        rank_offset = batch.get("rank_offset")
        U = uniq_rows.shape[0]

        if cfg.use_expand:
            rec_u, exp_u = pull_sparse_rows_extended(
                state.table, uniq_rows, lay, opt.embedx_threshold, cfg.pull_scale
            )
            pulled_u = jnp.concatenate([rec_u, exp_u], axis=1)  # [U, PW+E]
        else:
            pulled_u = pull_sparse_rows(
                state.table, uniq_rows, lay, opt.embedx_threshold, cfg.pull_scale
            )  # [U, PW]
        flat = jnp.take(pulled_u, inverse, axis=0)  # [L, PW(+E)]

        loss_w, loss_denom = ins_weight, None
        if cfg.adjust_ins_weight is not None and not eval_mode:
            loss_w, loss_denom = adjusted_loss_weight(
                cfg, flat, segments, ins_weight, B
            )
        loss, preds, gparams, gflat = local_forward_backward(
            model_apply, cfg, state.params, flat, segments, labels, dense,
            ins_weight=loss_w, rank_offset=rank_offset,
            loss_denom=loss_denom, eval_mode=eval_mode,
        )
        finite = None
        if cfg.check_nan and not eval_mode:
            gsum = loss + jnp.sum(gflat)
            for leaf in jax.tree.leaves(gparams):
                gsum = gsum + jnp.sum(leaf)
            finite = jnp.isfinite(gsum)
            if cfg.axis_name is not None:
                # all devices share the table: one bad device skips everywhere
                finite = (
                    jax.lax.psum((~finite).astype(jnp.int32), cfg.axis_name) == 0
                )
            # where, not multiply: NaN * 0 is still NaN
            gflat = jnp.where(finite, gflat, 0.0)
        if eval_mode:
            new_table = state.table
            new_params, new_opt_state = state.params, state.opt_state
            if cfg.axis_name is not None:
                loss = jax.lax.pmean(loss, cfg.axis_name)
        else:
            # --- sparse push: per-slot lr scaling happens at flat
            # resolution (a key deduped across slots gets each slot's
            # scaled contribution), then grads merge per unique row —
            # PushMergeCopy parity.
            guniq, show_counts, clk_counts = scale_and_merge_grads(
                cfg, gflat, segments, inverse, labels, num_segments=U,
                ins_weight=ins_weight,
            )
            if finite is not None:
                # a zeroed push is an exact identity on the table (adagrad
                # g2 += 0, step 0, show/clk += 0) — the skipped batch never
                # happened as far as the sparse model is concerned. where,
                # not multiply: a NaN label rides into clk via segment_sum
                show_counts = jnp.where(finite, show_counts, 0.0)
                clk_counts = jnp.where(finite, clk_counts, 0.0)

            new_table = push_sparse_rows(
                state.table, uniq_rows, guniq, show_counts, clk_counts, lay, opt
            )

            # --- dense sync: psum over the DP axis (K-step/NCCL allreduce
            # parity)
            if cfg.axis_name is not None:
                gparams = jax.lax.pmean(gparams, cfg.axis_name)
                loss = jax.lax.pmean(loss, cfg.axis_name)
            if cfg.dense_sync_mode == "async":
                # host AsyncDenseTable owns the dense optimizer: hand grads
                # back
                new_params, new_opt_state = state.params, state.opt_state
            else:
                updates, new_opt_state = dense_opt.update(
                    gparams, state.opt_state, state.params
                )
                new_params = optax.apply_updates(state.params, updates)
            if finite is not None:
                # skipped batch: dense params + optimizer moments stay put
                new_params = jax.tree.map(
                    lambda new, old: jnp.where(finite, new, old),
                    new_params, state.params,
                )
                new_opt_state = jax.tree.map(
                    lambda new, old: jnp.where(finite, new, old),
                    new_opt_state, state.opt_state,
                )

        auc_mask = None if ins_weight is None else (ins_weight > 0)
        if finite is not None:
            fin_mask = jnp.broadcast_to(finite, labels.shape)
            auc_mask = fin_mask if auc_mask is None else (auc_mask & fin_mask)
        new_auc = auc_update(state.auc, preds, labels, auc_mask)
        # a skipped batch never happened: the step counter (which paces
        # kstep param syncs and dump sampling) must not advance either
        step_inc = (
            jnp.ones((), jnp.int32) if finite is None else finite.astype(jnp.int32)
        )
        # preds/labels ride along for the host-side metric registry
        # (AddAucMonitor parity) — small [B] arrays, no sync forced
        metrics = {
            "loss": loss,
            "step": state.step + step_inc,
            "preds": preds,
            "labels": labels,
        }
        if finite is not None:
            metrics["nan_skipped"] = (~finite).astype(jnp.int32)
        if cfg.dense_sync_mode == "async" and not eval_mode:
            metrics["gparams"] = gparams
        return (
            TrainState(
                table=new_table,
                params=new_params,
                opt_state=new_opt_state,
                auc=new_auc,
                step=state.step + step_inc,
            ),
            metrics,
        )

    return step


def jit_train_step(step: Callable) -> Callable:
    """Single-device jit with table donation (in-place HBM update)."""
    return jax.jit(step, donate_argnums=(0,))
