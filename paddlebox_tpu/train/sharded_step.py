"""Mesh train step: DP batch × sharded table × replicated dense, one program.

The multi-device analog of train_step.py — what the reference spreads over
per-GPU worker threads + NCCL + the closed boxps MPI tier
(BoxPSWorker::TrainFiles boxps_worker.cc:420-466, SyncParam :359-398,
PullSparseGPU/PushSparseGPU box_wrapper_impl.h) compiles here into ONE
shard_map'd XLA program per step:

  per device: pull own buckets via all_to_all ──┐
  seqpool+CVM → model fwd/bwd                   │  ICI collectives,
  push grads via all_to_all to owner shards ────┤  XLA-scheduled
  dense grads psum (NCCL allreduce parity) ─────┘
  AUC accumulates into the device's own bucket slice (no host sync)

State placement: table [n_dev, cap, width] sharded on dp; AUC bucket tables
[n_dev, buckets] sharded on dp (summed at read time — collect_data_nccl
parity); dense params + optimizer state replicated.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.fleet.zero import Zero1Optimizer
from paddlebox_tpu.metrics.auc import AucState, auc_update
from paddlebox_tpu.parallel.mesh import (
    MeshPlan,
    put_replicated,
    put_sharded,
    shard_map,
)
from paddlebox_tpu.parallel.sharded_pullpush import sharded_pull, sharded_push
from paddlebox_tpu.train.train_step import (
    TrainState,
    TrainStepConfig,
    adjusted_loss_weight,
    local_forward_backward,
    scale_and_merge_grads,
)


def init_sharded_train_state(
    plan: MeshPlan,
    table: Any,  # np [n_dev, cap, width] from PassWorkingSet.finalize
    params: Any,
    dense_opt: optax.GradientTransformation,
    auc_buckets: int = 100_000,
    opt_state: Any = None,  # carry over between passes; None = fresh
    local_dense: bool = False,  # kstep/LocalSGD: per-device dense replicas
) -> TrainState:
    n = plan.n_devices
    # the dense trees are COPIED before placement: device_put to a
    # matching sharding ALIASES an already-placed array, and the jitted
    # step donates its state — without the copy, the first superstep
    # would delete the caller's params/opt_state leaves out from under
    # any other reference (a second-phase trainer sharing params, or the
    # trainer's own self.params after a mid-pass failure). Dense CTR
    # trees are small; the table deliberately is NOT copied (full-table
    # HBM) — its donation consuming the input is the intended handoff.
    params = jax.tree.map(jnp.copy, params)
    if opt_state is not None:
        opt_state = jax.tree.map(jnp.copy, opt_state)
    auc = AucState(
        pos=jnp.zeros((n, auc_buckets), jnp.int32),
        neg=jnp.zeros((n, auc_buckets), jnp.int32),
    )
    if isinstance(dense_opt, Zero1Optimizer):
        if local_dense:
            raise ValueError("ZeRO sharding and kstep local replicas conflict")
        dense_opt.check_axis(plan.axis, n)
        # moment chunks live dp-sharded: device i holds 1/n of the state
        opt_state = (
            opt_state if opt_state is not None else dense_opt.init_stacked(params)
        )
        return TrainState(
            table=put_sharded(plan, table),
            params=put_replicated(plan, params),
            opt_state=put_sharded(plan, opt_state),
            auc=put_sharded(plan, auc),
            step=put_replicated(plan, jnp.zeros((), jnp.int32)),
        )
    opt_state = opt_state if opt_state is not None else dense_opt.init(params)
    if local_dense:
        # K-step mode: every device carries its OWN dense params between
        # syncs, so they get a leading device axis sharded over the mesh
        # (the replicated layout would silently assume device-invariance)
        stack = lambda tree: jax.tree.map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x)[None], (n,) + jnp.shape(x)
            ),
            tree,
        )
        params_p = put_sharded(plan, stack(params))
        opt_p = put_sharded(plan, stack(opt_state))
    else:
        params_p = put_replicated(plan, params)
        opt_p = put_replicated(plan, opt_state)
    return TrainState(
        table=put_sharded(plan, table),
        params=params_p,
        opt_state=opt_p,
        auc=put_sharded(plan, auc),
        step=put_replicated(plan, jnp.zeros((), jnp.int32)),
    )


def make_local_mesh_step(
    model_apply: Callable,
    dense_opt: optax.GradientTransformation,
    cfg: TrainStepConfig,
    plan: MeshPlan,
    eval_mode: bool = False,
) -> Callable:
    """The PER-DEVICE mesh step body (runs inside shard_map).

    Factored out of make_sharded_train_step so the resident-feed tier can
    reuse the exact same numerics after building the batch on device; the
    host-packed path wraps it in shard_map directly. Batch fields carry a
    unit leading device axis (the dp shard of the global batch)."""
    if cfg.axis_name not in (None, plan.axis):
        raise ValueError(
            f"cfg.axis_name {cfg.axis_name!r} != mesh axis {plan.axis!r}; the "
            "sharded step always runs its collectives over the plan's axis"
        )
    is_async = cfg.dense_sync_mode == "async"
    is_zero = isinstance(dense_opt, Zero1Optimizer)
    if is_async and is_zero:
        raise ValueError(
            "dense_sync_mode='async' hands the dense optimizer to the host "
            "AsyncDenseTable — ZeRO state sharding has nothing to shard"
        )
    if is_zero and cfg.dense_sync_mode == "kstep":
        raise ValueError(
            "ZeRO state sharding needs identical (replicated) grads each "
            "step; kstep's local grads would diverge the chunks"
        )
    if is_zero:
        dense_opt.check_axis(plan.axis, plan.n_devices)
    lay, opt = cfg.layout, cfg.sparse_opt
    S, b = cfg.num_slots, cfg.batch_size
    ax = plan.axis

    def local_step(state: TrainState, batch: Dict[str, jnp.ndarray]):
        # strip the unit device axis of dp-sharded locals
        table = state.table[0]  # [cap, width]
        req_ranks = batch["req_ranks"][0]  # [n_shards, K]
        inverse = batch["inverse"][0]  # [L]
        segments = batch["segments"][0]  # [L]
        labels = batch["labels"][0]  # [b]
        dense = batch.get("dense")
        if dense is not None:
            dense = dense[0]
        ins_weight = batch.get("ins_weight")
        if ins_weight is not None:
            ins_weight = ins_weight[0]
        rank_offset = batch.get("rank_offset")
        if rank_offset is not None:
            rank_offset = rank_offset[0]
        n, K = req_ranks.shape

        pulled = sharded_pull(
            table, req_ranks, lay, opt.embedx_threshold, cfg.pull_scale, ax,
            extended=cfg.use_expand,
        )  # [n*K, PW(+E)]
        flat = jnp.take(pulled, inverse, axis=0)  # [L, PW(+E)]

        kstep = cfg.dense_sync_mode == "kstep"
        # weighted (pv/ghost) batches normalize by the GLOBAL weight sum, so
        # a device with more ghosts doesn't over-weight its real samples;
        # its local grads are then already global-mean scale (grad_div=1)
        # and the dense reduction is a psum of partial sums, not a pmean.
        # (This holds in kstep mode too — the sparse table is SHARED, so its
        # grads always need the global denominator; only the dense update
        # goes local, via a rescale below.)
        adjust = cfg.adjust_ins_weight is not None and not eval_mode
        if ins_weight is not None or adjust:
            # weighted loss normalizes by the GLOBAL real-instance count
            local_denom = (
                jnp.asarray(float(b))
                if ins_weight is None
                else jnp.sum(ins_weight)
            )
            loss_denom = jnp.maximum(jax.lax.psum(local_denom, ax), 1.0)
            grad_div = 1.0
        else:
            loss_denom = None
            grad_div = float(plan.n_devices)
        weighted = ins_weight is not None or adjust
        # kstep keeps per-device dense replicas, zero keeps per-device
        # moment chunks: both strip their leading device axis here
        params = (
            jax.tree.map(lambda x: x[0], state.params) if kstep else state.params
        )
        opt_state = (
            jax.tree.map(lambda x: x[0], state.opt_state)
            if (kstep or is_zero)
            else state.opt_state
        )
        loss_w = ins_weight
        if adjust:
            loss_w, _ = adjusted_loss_weight(cfg, flat, segments, ins_weight, b)
        loss, preds, gparams, gflat = local_forward_backward(
            model_apply, cfg, params, flat, segments, labels, dense,
            ins_weight=loss_w, rank_offset=rank_offset,
            loss_denom=loss_denom, eval_mode=eval_mode,
        )
        if eval_mode:
            loss = (
                jax.lax.psum(loss, ax)
                if ins_weight is not None
                else jax.lax.pmean(loss, ax)
            )
            local_auc = AucState(pos=state.auc.pos[0], neg=state.auc.neg[0])
            auc_mask = None if ins_weight is None else (ins_weight > 0)
            new_auc = auc_update(local_auc, preds, labels, auc_mask)
            return (
                state._replace(
                    auc=AucState(pos=new_auc.pos[None], neg=new_auc.neg[None]),
                    step=state.step + 1,
                ),
                {
                    "loss": loss,
                    "step": state.step + 1,
                    "preds": preds,
                    "labels": labels,
                },
            )
        finite = None
        if cfg.check_nan:
            gsum = loss + jnp.sum(gflat)
            for leaf in jax.tree.leaves(gparams):
                gsum = gsum + jnp.sum(leaf)
            # the table is shared via all_to_all: one poisoned device skips
            # the batch on EVERY device (check_nan_var_names parity)
            finite = jax.lax.psum(
                (~jnp.isfinite(gsum)).astype(jnp.int32), ax
            ) == 0
            gflat = jnp.where(finite, gflat, 0.0)  # where: NaN * 0 is NaN

        # grad_div rescales local-mean grads to GLOBAL-batch-mean so the
        # owner-side merge matches single-device semantics exactly and the
        # effective sparse LR is independent of mesh size
        gbucket, show_bucket, clk_bucket = scale_and_merge_grads(
            cfg,
            gflat,
            segments,
            inverse,
            labels,
            num_segments=n * K,
            grad_div=grad_div,
            ins_weight=ins_weight,
        )
        if finite is not None:
            # where, not multiply: a NaN label rides into clk via segment_sum
            show_bucket = jnp.where(finite, show_bucket, 0.0)
            clk_bucket = jnp.where(finite, clk_bucket, 0.0)

        new_table = sharded_push(
            table, req_ranks, gbucket, show_bucket, clk_bucket, lay, opt, ax
        )

        if kstep:
            # LocalSGD: dense update uses LOCAL grads. Weighted grads came
            # out against the global denominator (sparse correctness), so
            # rescale them to this device's local weighted mean.
            if weighted:
                local_w = (
                    jnp.asarray(float(b))
                    if ins_weight is None
                    else jnp.maximum(jnp.sum(ins_weight), 1.0)
                )
                gparams = jax.tree.map(lambda g: g * (loss_denom / local_w), gparams)
                loss = jax.lax.psum(loss, ax)
            else:
                loss = jax.lax.pmean(loss, ax)
        elif weighted:
            gparams = jax.lax.psum(gparams, ax)
            loss = jax.lax.psum(loss, ax)
        else:
            gparams = jax.lax.pmean(gparams, ax)
            loss = jax.lax.pmean(loss, ax)
        if is_async:
            # the host AsyncDenseTable owns the dense optimizer
            # (boxps_worker.cc:35-237 runs the same split under the full
            # multi-GPU trainer): the device never updates dense params —
            # the globally-reduced grads ride back in metrics and the
            # trainer's worker loop pushes them / pulls fresh params
            new_params, new_opt_state = state.params, state.opt_state
        elif is_zero:
            # each device updates its 1/n chunk, all_gather rebuilds the
            # full update (sharding meta-optimizer parity)
            updates, new_opt_state = dense_opt.update_local(
                gparams, opt_state, params
            )
            new_opt_state = jax.tree.map(lambda x: x[None], new_opt_state)
            new_params = optax.apply_updates(params, updates)
        else:
            updates, new_opt_state = dense_opt.update(gparams, opt_state, params)
            new_params = optax.apply_updates(params, updates)
        if kstep:
            # average params across the mesh every K steps (SyncParam scale
            # 1/(dev*node) parity) — the step counter is replicated, so the
            # cond is uniform and the collective inside it is deadlock-free
            new_params = jax.lax.cond(
                (state.step + 1) % cfg.param_sync_step == 0,
                lambda p: jax.tree.map(lambda x: jax.lax.pmean(x, ax), p),
                lambda p: p,
                new_params,
            )
            # restore the device axis for the sharded state layout
            new_params = jax.tree.map(lambda x: x[None], new_params)
            new_opt_state = jax.tree.map(lambda x: x[None], new_opt_state)

        if finite is not None:
            # skipped batch: dense side stays put (grads were NaN -> the
            # computed update is garbage; select the pre-step values)
            new_params = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                new_params, state.params,
            )
            new_opt_state = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old),
                new_opt_state, state.opt_state,
            )

        local_auc = AucState(pos=state.auc.pos[0], neg=state.auc.neg[0])
        auc_mask = None if ins_weight is None else (ins_weight > 0)
        if finite is not None:
            fin_mask = jnp.broadcast_to(finite, labels.shape)
            auc_mask = fin_mask if auc_mask is None else (auc_mask & fin_mask)
        new_auc = auc_update(local_auc, preds, labels, auc_mask)
        new_auc = AucState(pos=new_auc.pos[None], neg=new_auc.neg[None])

        # a skipped batch never happened: the step counter (which paces
        # the kstep param-sync cadence) must not advance either
        step_inc = (
            jnp.ones((), jnp.int32) if finite is None else finite.astype(jnp.int32)
        )
        metrics = {
            "loss": loss,
            "step": state.step + step_inc,
            "preds": preds,
            "labels": labels,
        }
        if finite is not None:
            metrics["nan_skipped"] = (~finite).astype(jnp.int32)
        if is_async:
            metrics["gparams"] = gparams  # globally reduced, replicated
        new_state = TrainState(
            table=new_table[None],
            params=new_params,
            opt_state=new_opt_state,
            auc=new_auc,
            step=state.step + step_inc,
        )
        return new_state, metrics

    return local_step


def mesh_state_specs(cfg: TrainStepConfig, dense_opt, plan: MeshPlan) -> TrainState:
    """PartitionSpecs of the sharded TrainState (shared by both feed tiers)."""
    dp, rep = P(plan.axis), P()
    kstep_mode = cfg.dense_sync_mode == "kstep"
    is_zero = isinstance(dense_opt, Zero1Optimizer)
    return TrainState(
        table=dp,
        params=dp if kstep_mode else rep,
        opt_state=dp if (kstep_mode or is_zero) else rep,
        auc=dp,
        step=rep,
    )


def mesh_metric_specs(cfg: TrainStepConfig, plan: MeshPlan, eval_mode: bool) -> Dict:
    dp, rep = P(plan.axis), P()
    metric_specs = {"loss": rep, "step": rep, "preds": dp, "labels": dp}
    if cfg.check_nan and not eval_mode:
        metric_specs["nan_skipped"] = rep  # psum'd -> uniform
    if cfg.dense_sync_mode == "async" and not eval_mode:
        # a pytree rides under one replicated spec (pytree-prefix rule)
        metric_specs["gparams"] = rep
    return metric_specs


def make_sharded_train_step(
    model_apply: Callable,
    dense_opt: optax.GradientTransformation,
    cfg: TrainStepConfig,
    plan: MeshPlan,
    eval_mode: bool = False,
) -> Callable:
    """Build jitted ``step(state, batch_dict) -> (state, metrics)`` on the mesh.

    ``cfg.batch_size`` is the PER-DEVICE batch; ``batch_dict`` fields come from
    ``pack_batch_sharded`` (req_ranks/inverse/segments/labels[/dense], all with
    a leading device axis) placed with ``plan.batch_sharding``.

    ``eval_mode`` (SetTestMode parity, box_wrapper.cc:623): forward +
    metrics only — the sharded pull/all_to_all still runs, but no push, no
    dense update; table/params/opt_state return bit-identical.
    """
    local_step = make_local_mesh_step(model_apply, dense_opt, cfg, plan, eval_mode)
    dp = P(plan.axis)
    state_specs = mesh_state_specs(cfg, dense_opt, plan)
    metric_specs = mesh_metric_specs(cfg, plan, eval_mode)

    def batch_specs(batch):
        return {k: dp for k in batch}

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
        mapped = shard_map(
            local_step,
            mesh=plan.mesh,
            in_specs=(state_specs, batch_specs(batch)),
            out_specs=(state_specs, metric_specs),
            check_vma=False,
        )
        return mapped(state, batch)

    return jax.jit(step, donate_argnums=(0,))


def kstep_sync_params(state: TrainState, plan: MeshPlan) -> TrainState:
    """Average the per-device dense replicas of a kstep state (the final
    SyncParam at pass end, boxps_worker.cc:459-461). The mean over the
    sharded device axis compiles to one all-reduce.

    Only valid on a state built with ``local_dense=True`` — the leading
    replica axis is checked against the mesh so a replicated ('step'-mode)
    state can't be silently averaged over its own first parameter dim.
    """
    n = plan.n_devices
    for leaf in jax.tree.leaves(state.params):
        if leaf.ndim < 1 or leaf.shape[0] != n:
            raise ValueError(
                f"param leaf shape {leaf.shape} has no leading [{n}] replica "
                "axis — kstep_sync_params needs a local_dense/kstep state"
            )
    avg = jax.tree.map(lambda x: jnp.mean(x, axis=0, keepdims=True), state.params)
    bcast = jax.tree.map(lambda x, a: jnp.broadcast_to(a, x.shape), state.params, avg)
    return state._replace(params=bcast)
