"""CheckpointManager: base+delta model publishing and day-level resume.

The reference checkpoints in the MODEL domain, not the tensor domain
(SURVEY.md §5): BoxPS ``SaveBase(path, date)`` writes the full sparse model,
``SaveDelta`` writes keys touched since the last save (box_wrapper.cc:
1288-1331, driven per pass via end_pass(need_save_delta)), dense params dump
from the worker scope at Finalize (boxps_trainer.cc:123-131), and resume is
``InitializeGPUAndLoadModel(model_path)`` + day staging (:1205, :1325).

Directory layout managed here:

    root/
      cursor.json                  {"date", "delta_idx"} — last durable state
      cursor.prev.json             the cursor this one replaced (fallback)
      <date>/base/                 full sparse snapshot (HostSparseTable dir)
      <date>/delta-NNNN/           touched-keys snapshots, applied in order
      <date>/dense-NNNN.npz        dense params + optimizer state per save

Durability discipline (the robustness tentpole):

- Sparse snapshot dirs are written to a ``.tmp`` sibling, stamped with a
  ``manifest.json`` carrying per-file size+CRC32, and published atomically
  via ``os.replace`` — a crash mid-save can never leave a half-written dir
  under the final name.
- The cursor is rewritten (atomically) only after every artifact it names
  is durable, so the crash window between any two writes leaves the cursor
  pointing at the previous consistent (sparse, dense) pair.
- ``resume()`` verifies manifests before trusting a snapshot and walks
  back to the newest consistent state (shorter delta chain, or the
  previous cursor) instead of loading a torn one.

Injection sites (utils/faultinject): ``checkpoint.save`` fires at each
durability boundary inside save_base/save_delta (hit counts select a crash
window — see docs/ROBUSTNESS.md); ``checkpoint.load`` fires in resume()
before the base load and before each delta apply.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
import zlib
from typing import Any, Dict, Optional

from paddlebox_tpu.table.sparse_table import HostSparseTable
from paddlebox_tpu.utils.faultinject import fire as _fault_fire
from paddlebox_tpu.utils.fs import atomic_write
from paddlebox_tpu.utils.monitor import STAT_ADD

logger = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
LATEST_NAME = "latest.json"


class DeltaLineageError(RuntimeError):
    """A delta publish or apply that does not extend the recorded lineage.

    Deltas are meaningful only as an ordered chain over one base: a gap in
    the chain, a rewound index, or a watermark whose listed dirs disagree
    with its own (date, delta_idx) all mean some writer skipped the
    protocol. Producers refuse to publish over a broken chain; followers
    refuse to apply one — silently proceeding would serve a model state
    no trainer ever held.
    """


class MembershipEpochError(DeltaLineageError):
    """A delta chain spanning more than one ownership epoch.

    Each delta snapshots the keys ONE rank owned when it was published; if
    ownership re-sharded mid-chain (rank death, planned migration), deltas
    before and after the flip cover different key ranges and their
    composition is not any state one trainer held. Producers refuse to
    extend a chain across an epoch flip (they re-anchor with a fresh base
    instead), and ``validate_watermark`` rejects a mixed-epoch chain with
    this typed error so a follower alarms instead of serving a chimera.
    """


def rank_root(root: str, rank: int) -> str:
    """Per-rank checkpoint root under a shared day root.

    Every rank publishes its owned shard slice under ``rank-<r>`` so a
    survivor can open a DEAD rank's chain read-only and adopt its ranges
    through the same manifest-verified resume path (membership epoch
    protocol, docs/ROBUSTNESS.md)."""
    return os.path.join(root, f"rank-{int(rank)}")


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def write_manifest(snap_dir: str) -> str:
    """Stamp ``snap_dir`` with per-file size+CRC32 over its current
    contents. Written atomically (tmp + replace) so a torn manifest can
    never pass for a complete one."""
    files: Dict[str, Dict[str, int]] = {}
    for name in sorted(os.listdir(snap_dir)):
        p = os.path.join(snap_dir, name)
        if name == MANIFEST_NAME or not os.path.isfile(p):
            continue
        files[name] = {"size": os.path.getsize(p), "crc32": _file_crc32(p)}
    mpath = os.path.join(snap_dir, MANIFEST_NAME)
    with atomic_write(mpath) as f:
        json.dump({"files": files}, f)
    return mpath


def verify_snapshot(snap_dir: str, require_manifest: bool = False) -> bool:
    """True iff ``snap_dir`` holds a complete, uncorrupted snapshot.

    Every manifest entry must exist with the recorded size and CRC32. A
    dir without a manifest is a pre-manifest (legacy) snapshot: accepted
    unless ``require_manifest`` (counted so operators can see unverified
    loads), since refusing would brick every old checkpoint tree."""
    if not os.path.isdir(snap_dir):
        return False
    mpath = os.path.join(snap_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        if require_manifest:
            return False
        STAT_ADD("ckpt_unverified_snapshots")
        return os.path.exists(os.path.join(snap_dir, "meta.json"))
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        for name, want in manifest["files"].items():
            p = os.path.join(snap_dir, name)
            if not os.path.exists(p):
                return False
            if os.path.getsize(p) != want["size"]:
                return False
            if _file_crc32(p) != want["crc32"]:
                return False
    except (OSError, ValueError, KeyError):
        # a torn/unreadable manifest is a FAILED verification, not a mere
        # "no": resume walks on to an older snapshot, which operators
        # should see happening
        STAT_ADD("ckpt_verify_failures")
        return False
    return True


def _manifest_crc(snap_dir: str) -> Optional[int]:
    """CRC32 of a snapshot's manifest file (None when unstamped). Pins the
    watermark to one exact publish of each snapshot: a re-published dir
    under the same name gets a new manifest CRC, so a follower can tell
    'same chain link' from 'same path, different contents'."""
    mpath = os.path.join(snap_dir, MANIFEST_NAME)
    try:
        return _file_crc32(mpath)
    # absence probe: None is the answer (no manifest, legacy snapshot)
    # pbox-lint: disable=EXC007
    except OSError:
        return None


def read_watermark(root: str) -> Optional[Dict[str, Any]]:
    """The published ``latest.json`` under ``root``, or None when absent
    or torn (a torn watermark reads as 'nothing published yet', never as
    garbage — the same discipline as cursor reads)."""
    path = os.path.join(root, LATEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    # absent-or-torn watermark reads as None by design: the atomic
    # publish means a reader never has to distinguish the two
    # pbox-lint: disable=EXC007
    except (OSError, ValueError):
        return None


def validate_watermark(wm: Dict[str, Any]) -> None:
    """Structural + lineage check of a watermark; raises
    :class:`DeltaLineageError` when the listed chain is not exactly
    base + delta-0001..delta-NNNN for the watermark's own (date, delta_idx).
    """
    try:
        date = wm["date"]
        idx = int(wm["delta_idx"])
        base = wm["base"]["path"]
        deltas = [d["path"] for d in wm["deltas"]]
    except (KeyError, TypeError, ValueError) as e:
        raise DeltaLineageError(f"malformed watermark {wm!r}: {e}") from e
    if idx < 0:
        raise DeltaLineageError(f"watermark delta_idx {idx} is negative")
    # one chain, one ownership epoch: entries published under different
    # epochs cover different key ranges and must never compose
    chain_entries = [wm["base"]] + list(wm["deltas"])
    if isinstance(wm.get("compact"), dict):
        chain_entries.append(wm["compact"])
    epochs = {
        e.get("ownership_epoch")
        for e in chain_entries
        if isinstance(e, dict) and "ownership_epoch" in e
    }
    if len(epochs) > 1:
        raise MembershipEpochError(
            f"watermark chain for {date!r} mixes ownership epochs "
            f"{sorted(epochs)} — an epoch flip must re-anchor with a new "
            "base, not extend the old chain"
        )
    if base != f"{date}/base":
        raise DeltaLineageError(
            f"watermark base {base!r} does not belong to date {date!r}"
        )
    want = [f"{date}/delta-{i:04d}" for i in range(1, idx + 1)]
    if deltas != want:
        raise DeltaLineageError(
            f"watermark delta chain {deltas} is out of lineage — "
            f"delta_idx {idx} requires exactly {want} (ordered, gap-free)"
        )
    comp = wm.get("compact")
    if comp is not None:
        # optional fast-forward artifact: a fold of base+delta-0001..covers.
        # It substitutes for a chain PREFIX, so it must name a link the
        # chain actually has — otherwise a follower could fast-forward past
        # state this watermark never published.
        try:
            covers = int(comp["covers"])
            cpath = comp["path"]
        except (KeyError, TypeError, ValueError) as e:
            raise DeltaLineageError(f"malformed compact entry {comp!r}: {e}") from e
        if not 1 <= covers <= idx or cpath != f"{date}/compact-{covers:04d}":
            raise DeltaLineageError(
                f"compact entry {comp!r} is out of lineage for {date!r} at "
                f"delta_idx {idx}"
            )


class CheckpointManager:
    def __init__(self, root: str):
        self.root = root
        # the key-ownership epoch this manager currently publishes under
        # (parallel/membership.py); single-host stays at 0. Set by the
        # supervisor when membership changes — the next save_base
        # re-anchors the chain, and save_delta refuses to straddle a flip.
        self.ownership_epoch = 0
        # the live rank set the publishing epoch corresponds to (None =
        # non-elastic). Also supervisor-set; surfaced in the watermark so
        # a follower (or a joining rank) can see the fleet size a chain
        # was published under without parsing ownership maps.
        self.live_ranks: Optional[list] = None
        # streaming-plane provenance (train/stream.py): when the publisher
        # is a StreamSupervisor it stamps {"cut_seq", "oldest_unix",
        # "records"} here before each save so the watermark carries the
        # ingest timestamp of the oldest record in the publish — the
        # follower turns that into the serve.freshness_s histogram.
        self.stream_meta: Optional[Dict[str, Any]] = None
        os.makedirs(root, exist_ok=True)

    # ---- paths -----------------------------------------------------------

    def _day(self, date: str) -> str:
        return os.path.join(self.root, date)

    def _cursor_path(self) -> str:
        return os.path.join(self.root, "cursor.json")

    def _prev_cursor_path(self) -> str:
        return os.path.join(self.root, "cursor.prev.json")

    def _read_cursor(self, path: str) -> Optional[Dict[str, Any]]:
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        # pbox-lint: disable=EXC007 — same contract as read_watermark
        except (OSError, ValueError):
            return None  # a torn cursor reads as absent, never as garbage

    def cursor(self) -> Optional[Dict[str, Any]]:
        return self._read_cursor(self._cursor_path())

    def prev_cursor(self) -> Optional[Dict[str, Any]]:
        return self._read_cursor(self._prev_cursor_path())

    def _write_cursor(
        self,
        date: str,
        delta_idx: int,
        dense: Optional[str],
        compact: Optional[int] = None,
    ) -> None:
        cur = {
            "date": date,
            "delta_idx": delta_idx,
            "ownership_epoch": self.ownership_epoch,
        }
        if dense is not None:
            cur["dense"] = dense  # the dense file this sparse state pairs with
        if compact:
            # newest fold of base+delta-0001..compact; carried forward by
            # save_delta, reset by save_base (a new chain has no fold yet)
            cur["compact"] = int(compact)
        # keep the superseded cursor as the fallback anchor: if every
        # artifact of the NEW state later verifies torn (bit rot, torn
        # copy), resume() can still land on the previous consistent state
        old = self.cursor()
        if old is not None and old != cur:
            with atomic_write(self._prev_cursor_path()) as f:
                json.dump(old, f)
        with atomic_write(self._cursor_path()) as f:  # crash-safe cursor
            json.dump(cur, f)
        # the cursor is the trainer's resume anchor; the watermark is the
        # FOLLOWER-facing view of the same commit. Published strictly after
        # the cursor, so a watermark never names a state the producer
        # itself would not resume into.
        self._publish_watermark(cur)

    # ---- follower watermark ---------------------------------------------

    def _latest_path(self) -> str:
        return os.path.join(self.root, LATEST_NAME)

    def _publish_watermark(self, cur: Dict[str, Any]) -> None:
        """Atomically publish ``latest.json``: the base + ordered delta
        chain (each entry pinned by its manifest CRC32) plus the paired
        dense file. atomic_write means a tailing follower either sees the
        previous complete watermark or this one — never a half-published
        save."""
        date, idx = cur["date"], cur["delta_idx"]
        epoch = int(cur.get("ownership_epoch", 0))

        def entry(rel: str) -> Dict[str, Any]:
            return {
                "path": rel,
                "manifest_crc": _manifest_crc(os.path.join(self.root, rel)),
                # save_delta refuses to straddle an epoch flip, so every
                # entry of one chain carries the base's epoch — a follower
                # validates exactly that (validate_watermark)
                "ownership_epoch": epoch,
            }

        wm: Dict[str, Any] = {
            "date": date,
            "delta_idx": idx,
            "ownership_epoch": epoch,
            "base": entry(f"{date}/base"),
            "deltas": [entry(f"{date}/delta-{i:04d}") for i in range(1, idx + 1)],
            "published_unix": time.time(),
        }
        if self.live_ranks is not None:
            wm["live_ranks"] = [int(r) for r in self.live_ranks]
        dense = cur.get("dense")
        if dense is not None:
            dpath = os.path.join(self._day(date), dense)
            wm["dense"] = {
                "path": f"{date}/{dense}",
                "crc32": _file_crc32(dpath) if os.path.exists(dpath) else None,
            }
        comp = int(cur.get("compact") or 0)
        if comp >= 1:
            rel = f"{date}/compact-{comp:04d}"
            wm["compact"] = {
                "path": rel,
                "covers": comp,
                "manifest_crc": _manifest_crc(os.path.join(self.root, rel)),
                "ownership_epoch": epoch,
            }
        if self.stream_meta is not None:
            wm["stream"] = dict(self.stream_meta)
        with atomic_write(self._latest_path()) as f:
            json.dump(wm, f)
        STAT_ADD("ckpt_watermark_publishes")

    def read_watermark(self) -> Optional[Dict[str, Any]]:
        return read_watermark(self.root)

    # ---- save ------------------------------------------------------------

    def _publish_snapshot(self, write_fn, final_dir: str) -> None:
        """tmp dir -> write_fn -> manifest -> atomic rename to final_dir.

        A crash anywhere before the rename leaves only the ``.tmp``
        sibling; the final name either doesn't exist or holds the complete
        previous snapshot. Retried saves clear stale tmp leftovers."""
        tmp = final_dir + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)  # torn leftover from a failed attempt
        os.makedirs(tmp, exist_ok=True)
        write_fn(tmp)
        _fault_fire("checkpoint.save")  # window: sparse written, unpublished
        write_manifest(tmp)
        if os.path.isdir(final_dir):
            # a complete snapshot is being overwritten (re-save of the same
            # pass after a downstream failure): drop it just before the
            # rename — the cursor never points here until we finish
            shutil.rmtree(final_dir)
        os.replace(tmp, final_dir)

    def save_base(self, date: str, table: HostSparseTable, trainer=None) -> str:
        """Full sparse snapshot + dense (SaveBase parity). Resets the day's
        delta counter — deltas are relative to this base."""
        _fault_fire("checkpoint.save")  # window: nothing written yet
        day = self._day(date)
        base_dir = os.path.join(day, "base")
        self._publish_snapshot(table.save_base, base_dir)
        _fault_fire("checkpoint.save")  # window: sparse published, no dense
        dense = None
        if trainer is not None:
            dense = "dense-0000.npz"
            trainer.save_dense(os.path.join(day, dense))
        _fault_fire("checkpoint.save")  # window: all durable, cursor stale
        self._write_cursor(date, delta_idx=0, dense=dense)
        return base_dir

    def save_delta(self, date: str, table: HostSparseTable, trainer=None) -> str:
        """Touched-keys snapshot (SaveDelta / xbox online-publish parity).

        Requires a base for ``date`` (deltas apply on top of it in order).
        Each save writes its OWN dense file, named in the cursor only after
        both sparse and dense are durable — a crash between the two can
        never publish a sparse/dense skew (the cursor still points at the
        previous consistent pair).
        """
        cur = self.cursor()
        if cur is None or cur["date"] != date:
            raise RuntimeError(
                f"no base saved for date {date!r} — save_base first "
                "(deltas are relative to a base)"
            )
        if int(cur.get("ownership_epoch", 0)) != int(self.ownership_epoch):
            raise MembershipEpochError(
                f"chain for {date!r} was published under ownership epoch "
                f"{cur.get('ownership_epoch', 0)} but this rank is now at "
                f"epoch {self.ownership_epoch} — save_base to re-anchor "
                "(a delta must not straddle a membership flip)"
            )
        _fault_fire("checkpoint.save")  # window: nothing written yet
        idx = cur["delta_idx"] + 1
        day = self._day(date)
        missing = [
            i for i in range(1, idx)
            if not os.path.isdir(os.path.join(day, f"delta-{i:04d}"))
        ]
        if missing:
            # the cursor promises a contiguous chain; a hole means someone
            # deleted mid-chain links — publishing delta N on top would
            # hand followers a chain no trainer state corresponds to
            raise DeltaLineageError(
                f"cursor for {date} is at delta_idx {idx - 1} but delta "
                f"dir(s) {missing} are missing — refusing an out-of-lineage "
                "publish (restore the chain or save_base to start a new one)"
            )
        path = os.path.join(day, f"delta-{idx:04d}")
        # defer the touched-set clear until the cursor commits: a save that
        # crashes after publishing (but before the cursor names it) retries
        # with the SAME touched keys instead of snapshotting an empty delta
        # over the published one
        self._publish_snapshot(
            lambda d: table.save_delta(d, clear_touched=False), path
        )
        _fault_fire("checkpoint.save")  # window: delta published, no dense
        dense = cur.get("dense")
        if trainer is not None:
            dense = f"dense-{idx:04d}.npz"
            trainer.save_dense(os.path.join(day, dense))
        _fault_fire("checkpoint.save")  # window: all durable, cursor stale
        self._write_cursor(
            date, delta_idx=idx, dense=dense, compact=cur.get("compact")
        )
        table.clear_touched()  # delta committed: keys count as saved now
        # retire dense files older than the previous cursor (keep one back
        # for safety against torn reads of cursor.json readers) — but never
        # the file the new cursor itself references (deltas saved with
        # trainer=None carry the older dense name forward)
        for i in range(idx - 1):
            name = f"dense-{i:04d}.npz"
            if name == dense:
                continue
            stale = os.path.join(day, name)
            if os.path.exists(stale):
                try:
                    os.remove(stale)
                except OSError as e:
                    # a leaked dense file is an ops problem (disk creep on
                    # multi-day runs) — count it and say which file
                    STAT_ADD("ckpt_dense_retire_failures")
                    logger.warning(
                        "failed to retire stale dense checkpoint %s: %s",
                        stale, e,
                    )
        return path

    # ---- compaction ------------------------------------------------------

    def compact(self, date: str, scratch: HostSparseTable) -> Optional[str]:
        """Fold base + delta-0001..N into one full snapshot ``compact-NNNN``.

        The streaming plane publishes a delta per micro-pass, so a chain
        grows O(minutes-since-base) links; the fold caps follower catch-up
        and trainer resume at one full load + the post-fold tail. The fold
        is an exact sequential replay of the chain into ``scratch`` (a
        fresh, EMPTY table with the live table's layout/opt/shards): each
        delta apply performs its own decay catch-up step exactly as a
        follower would, so the materialized state — published via
        ``save_base`` as a full kind="base" snapshot — is bitwise-equal to
        applying the chain, by construction. (A touched-keys re-snapshot
        would NOT be: per-micro-pass decay is stepwise fp32 ``v*r*r*...``,
        not one ``v*r**n``.)

        Crash discipline mirrors save_delta (fault site ``ckpt.compact``):
        the fold publishes atomically under ``compact-NNNN`` and only then
        does the cursor (and watermark) name it — any crash leaves the old
        chain servable bitwise, and a healed retry refolds to the identical
        artifact. Like ``save_delta`` it refuses to straddle an ownership-
        epoch flip: a fold of a pre-flip chain is state no current trainer
        holds. Old delta dirs are NOT deleted (the uncompacted chain stays
        valid; lineage validation is unchanged).

        Returns the published dir, or None when there is nothing new to
        fold (idempotent).
        """
        cur = self.cursor()
        if cur is None or cur["date"] != date:
            raise RuntimeError(
                f"no chain for date {date!r} to compact — save_base first"
            )
        if int(cur.get("ownership_epoch", 0)) != int(self.ownership_epoch):
            raise MembershipEpochError(
                f"chain for {date!r} was published under ownership epoch "
                f"{cur.get('ownership_epoch', 0)} but this rank is now at "
                f"epoch {self.ownership_epoch} — a compact must not "
                "straddle a membership flip (save_base re-anchors first)"
            )
        n = int(cur["delta_idx"])
        if n < 1 or int(cur.get("compact") or 0) >= n:
            return None
        _fault_fire("ckpt.compact")  # window: nothing read yet
        day = self._day(date)
        links = [os.path.join(day, "base")] + [
            os.path.join(day, f"delta-{i:04d}") for i in range(1, n + 1)
        ]
        for link in links:
            # CRC-pinned replay: folding a torn link would LAUNDER the
            # corruption into a snapshot that then verifies clean
            if not verify_snapshot(link):
                raise DeltaLineageError(
                    f"refusing to compact over torn chain link {link!r}"
                )
        scratch.load(links[0])
        for link in links[1:]:
            scratch.apply_delta(link)
        _fault_fire("ckpt.compact")  # window: folded in memory, unpublished
        comp_dir = os.path.join(day, f"compact-{n:04d}")
        self._publish_snapshot(scratch.save_base, comp_dir)
        _fault_fire("ckpt.compact")  # window: published, cursor stale
        # re-read: the chain may have grown while we folded — the fold
        # still covers exactly n, the tail stays as deltas
        cur = self.cursor() or cur
        self._write_cursor(
            cur["date"], cur["delta_idx"], cur.get("dense"), compact=n
        )
        STAT_ADD("ckpt_compactions")
        return comp_dir

    # ---- resume ----------------------------------------------------------

    def _consistent_state(self, cur: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Verify ``cur``'s artifacts; return the newest consistent state
        reachable from it (possibly a shorter delta chain), or None when
        even the base is torn/missing."""
        day = self._day(cur["date"])
        # a verified compact fold substitutes for the chain PREFIX it
        # covers, so it rescues states the classic walk cannot reach: a
        # torn base, or a torn mid-chain delta <= covers. When both paths
        # are whole they load bitwise-identical state (compact invariant);
        # the fold is preferred because it applies fewer links.
        covers = int(cur.get("compact") or 0)
        comp_ok = covers >= 1 and verify_snapshot(
            os.path.join(day, f"compact-{covers:04d}")
        )
        if comp_ok:
            m = covers
        elif verify_snapshot(os.path.join(day, "base")):
            m = 0
        else:
            return None
        for i in range(m + 1, cur["delta_idx"] + 1):
            if not verify_snapshot(os.path.join(day, f"delta-{i:04d}")):
                break  # deltas apply in order: a torn link truncates the chain
            m = i
        dense = cur.get("dense")
        if m < cur["delta_idx"]:
            # walked back: the cursor's dense pairs with the full chain, so
            # re-pair with the newest surviving dense at or below m
            dense = None
            for i in range(m, -1, -1):
                name = f"dense-{i:04d}.npz"
                if os.path.exists(os.path.join(day, name)):
                    dense = name
                    break
        state = {
            "date": cur["date"],
            "delta_idx": m,
            "dense": dense,
            # the epoch this chain was published under: shard adoption
            # compares it against the live map to detect a chain that
            # predates the last ownership flip (membership.py)
            "ownership_epoch": int(cur.get("ownership_epoch", 0)),
        }
        if comp_ok:
            # load compact-NNNN in place of base + delta-0001..NNNN;
            # absent when no verified fold is in play
            state["compact"] = covers
        return state

    def resume(self, table: HostSparseTable, trainer=None) -> Optional[Dict[str, Any]]:
        """Rebuild the newest durable state into ``table`` (+ trainer dense).

        Every snapshot is manifest-verified before it is trusted: a torn
        delta truncates the chain to the last consistent link, a torn base
        falls back to the previous cursor's state — resume never loads a
        half-written snapshot. Returns the state actually loaded
        ({"date", "delta_idx", ...}) or None when nothing consistent was
        ever saved (cold start).
        """
        cur = self.cursor()
        if cur is None:
            # a torn/missing cursor with an intact predecessor is a crash
            # mid-rotation, not a cold start — resume from the predecessor
            cur = self.prev_cursor()
            if cur is None:
                return None
            STAT_ADD("ckpt_resume_fallbacks")
            logger.warning("cursor unreadable; resuming from prev cursor %s", cur)
        state = self._consistent_state(cur)
        if state is None or state["delta_idx"] < cur["delta_idx"]:
            STAT_ADD("ckpt_resume_fallbacks")
            logger.warning(
                "checkpoint state %s is torn; falling back (candidate: %s)",
                cur, state,
            )
        if state is None:
            prev = self.prev_cursor()
            if prev is not None:
                state = self._consistent_state(prev)
            if state is None:
                raise RuntimeError(
                    f"no consistent checkpoint reachable from cursor {cur} "
                    f"(prev {self.prev_cursor()}) — every candidate snapshot "
                    "failed manifest verification"
                )
        day = self._day(state["date"])
        comp = int(state.get("compact") or 0)
        _fault_fire("checkpoint.load")
        if comp >= 1:
            # the fold is a full kind="base" snapshot of base+delta-0001..
            # comp — bitwise-equal to replaying that prefix, loaded in one
            table.load(os.path.join(day, f"compact-{comp:04d}"))
            STAT_ADD("ckpt_compact_resumes")
        else:
            table.load(os.path.join(day, "base"))
        for i in range(comp + 1, state["delta_idx"] + 1):
            _fault_fire("checkpoint.load")
            table.apply_delta(os.path.join(day, f"delta-{i:04d}"))
        # per-save dense file named in the cursor; "dense.npz" is the
        # pre-versioning layout (older checkpoints)
        dense = os.path.join(day, state.get("dense") or "dense.npz")
        if trainer is not None and os.path.exists(dense):
            if trainer.params is None:
                trainer.init_params()
            trainer.load_dense(dense)
        return state
