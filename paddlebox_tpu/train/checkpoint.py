"""CheckpointManager: base+delta model publishing and day-level resume.

The reference checkpoints in the MODEL domain, not the tensor domain
(SURVEY.md §5): BoxPS ``SaveBase(path, date)`` writes the full sparse model,
``SaveDelta`` writes keys touched since the last save (box_wrapper.cc:
1288-1331, driven per pass via end_pass(need_save_delta)), dense params dump
from the worker scope at Finalize (boxps_trainer.cc:123-131), and resume is
``InitializeGPUAndLoadModel(model_path)`` + day staging (:1205, :1325).

Directory layout managed here:

    root/
      cursor.json                  {"date", "delta_idx"} — last durable state
      <date>/base/                 full sparse snapshot (HostSparseTable dir)
      <date>/delta-NNNN/           touched-keys snapshots, applied in order
      <date>/dense.npz             dense params + optimizer state

``resume()`` rebuilds the newest durable state: load the cursor date's base,
apply its deltas in order, restore dense — then training re-enters at the
next pass with deterministic file striping (the reference's day-level
re-entry model).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from paddlebox_tpu.table.sparse_table import HostSparseTable


class CheckpointManager:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # ---- paths -----------------------------------------------------------

    def _day(self, date: str) -> str:
        return os.path.join(self.root, date)

    def _cursor_path(self) -> str:
        return os.path.join(self.root, "cursor.json")

    def cursor(self) -> Optional[Dict[str, Any]]:
        p = self._cursor_path()
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    def _write_cursor(self, date: str, delta_idx: int, dense: Optional[str]) -> None:
        tmp = self._cursor_path() + ".tmp"
        cur = {"date": date, "delta_idx": delta_idx}
        if dense is not None:
            cur["dense"] = dense  # the dense file this sparse state pairs with
        with open(tmp, "w") as f:
            json.dump(cur, f)
        os.replace(tmp, self._cursor_path())  # atomic: crash-safe cursor

    # ---- save ------------------------------------------------------------

    def save_base(self, date: str, table: HostSparseTable, trainer=None) -> str:
        """Full sparse snapshot + dense (SaveBase parity). Resets the day's
        delta counter — deltas are relative to this base."""
        day = self._day(date)
        table.save_base(os.path.join(day, "base"))
        dense = None
        if trainer is not None:
            dense = "dense-0000.npz"
            trainer.save_dense(os.path.join(day, dense))
        self._write_cursor(date, delta_idx=0, dense=dense)
        return os.path.join(day, "base")

    def save_delta(self, date: str, table: HostSparseTable, trainer=None) -> str:
        """Touched-keys snapshot (SaveDelta / xbox online-publish parity).

        Requires a base for ``date`` (deltas apply on top of it in order).
        Each save writes its OWN dense file, named in the cursor only after
        both sparse and dense are durable — a crash between the two can
        never publish a sparse/dense skew (the cursor still points at the
        previous consistent pair).
        """
        cur = self.cursor()
        if cur is None or cur["date"] != date:
            raise RuntimeError(
                f"no base saved for date {date!r} — save_base first "
                "(deltas are relative to a base)"
            )
        idx = cur["delta_idx"] + 1
        day = self._day(date)
        path = os.path.join(day, f"delta-{idx:04d}")
        table.save_delta(path)
        dense = cur.get("dense")
        if trainer is not None:
            dense = f"dense-{idx:04d}.npz"
            trainer.save_dense(os.path.join(day, dense))
        self._write_cursor(date, delta_idx=idx, dense=dense)
        # retire dense files older than the previous cursor (keep one back
        # for safety against torn reads of cursor.json readers) — but never
        # the file the new cursor itself references (deltas saved with
        # trainer=None carry the older dense name forward)
        for i in range(idx - 1):
            name = f"dense-{i:04d}.npz"
            if name == dense:
                continue
            stale = os.path.join(day, name)
            if os.path.exists(stale):
                try:
                    os.remove(stale)
                except OSError:
                    pass
        return path

    # ---- resume ----------------------------------------------------------

    def resume(self, table: HostSparseTable, trainer=None) -> Optional[Dict[str, Any]]:
        """Rebuild the newest durable state into ``table`` (+ trainer dense).

        Returns the cursor ({"date", "delta_idx"}) or None when nothing was
        ever saved (cold start).
        """
        cur = self.cursor()
        if cur is None:
            return None
        day = self._day(cur["date"])
        table.load(os.path.join(day, "base"))
        for i in range(1, cur["delta_idx"] + 1):
            table.apply_delta(os.path.join(day, f"delta-{i:04d}"))
        # per-save dense file named in the cursor; "dense.npz" is the
        # pre-versioning layout (older checkpoints)
        dense = os.path.join(day, cur.get("dense") or "dense.npz")
        if trainer is not None and os.path.exists(dense):
            if trainer.params is None:
                trainer.init_params()
            trainer.load_dense(dense)
        return cur
