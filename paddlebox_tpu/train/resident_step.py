"""Device-resident pass feed: upload the pass once, feed only indices.

The classic feed path ships ~10 bytes/key/batch (uniq_rows + inverse +
segments) from host to device every batch — the MiniBatchGpuPack H2D copy
(data_feed.h:1492-1504), fine over PCIe, dominant over a bandwidth-limited
host<->TPU transport. This path exploits what the reference cannot: the
whole pass is immutable once `begin_pass` runs (PadBoxSlotDataset keeps
`input_records_` frozen for the pass, data_set.cc:1628-1683), so the
row-resolved key stream can live in device HBM for the pass:

- **Upload once per pass**: flat row ids for every key of every record
  (`rows`), per-record per-slot absolute offsets (`off`), labels, optional
  dense features. ~8 bytes/key, once.
- **Per batch**: feed is ONE [B] int32 record-index vector (~16 KB). The
  jitted step rebuilds the batch on device: ragged gather via
  cumsum+searchsorted, then cross-slot dedup via sort + segment scan
  (DedupKeysAndFillIdx parity, box_wrapper_impl.h:103 — the reference runs
  the same dedup as a device kernel, not on the host).
- **Superstep**: `lax.scan` over K batches per dispatch amortizes the
  host->device dispatch round-trip (BoxPSWorker's batch loop
  boxps_worker.cc:420-466 collapses into one XLA program per K batches).

The produced per-batch arrays are bit-compatible with BatchPacker.pack
(same slot-major flat order, same padding conventions), and the train-step
body is REUSED from train_step.make_train_step — the resident tier changes
where the batch is assembled, never what the step computes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.data.device_pack import _round_bucket
from paddlebox_tpu.train.train_step import TrainStepConfig, make_train_step

config.define_flag(
    "enable_resident_feed",
    1,
    "keep the pass's row stream resident in device HBM and feed only "
    "record indices per batch (single-device and single-host-mesh fast "
    "path; 0 = classic per-batch host packing)",
)
config.define_flag(
    "resident_scan_batches",
    8,
    "minibatches per dispatched superstep (lax.scan length); higher "
    "amortizes dispatch latency, lower returns metrics sooner",
)


class ResidentPass:
    """Pass-scoped device arrays + static pad shapes for the resident feed.

    Built once per (store, working set); ~8 bytes/key of HBM. ``ensure``
    grows the frozen pad shapes to cover a batch partition (sticky, like
    BatchPacker.freeze_shapes — one compiled program per pass).
    """

    def __init__(
        self,
        store,  # ColumnarRecords
        ws,  # PassWorkingSet (finalized)
        schema,
        dense_slot: Optional[str] = None,
        dense_dim: int = 0,
        label_slot: Optional[str] = None,
        bucket: Optional[int] = None,
        plan=None,  # MeshPlan; needed only multi-host
        transport=None,  # host plane; multi-host placement + lockstep
    ):
        self.store = store
        self.ws = ws
        self.num_slots = store.n_sparse
        self.bucket = bucket or config.get_flag("batch_bucket_rounding")
        self.n_table_rows = ws.n_mesh_shards * ws.capacity
        self.pad_row = self.n_table_rows - 1
        rows = store.resolve_rows(ws)
        if len(store.u64_values) >= (1 << 31):  # int32 src indexing
            raise ValueError("pass too large for resident feed (>=2^31 keys)")
        self._host_rows = rows
        self._key_counts = store.key_counts()
        self.transport = transport
        # multi-host: every host holds a DIFFERENT pass (its local records),
        # so the resident arrays can't replicate — each device carries its
        # own host's copy ([n_dev, ...] device-axis sharded, sizes
        # allreduce-max-padded so every host builds the same global shape)
        self.per_device = (
            plan is not None
            and transport is not None
            and transport.n_ranks > 1
        )

        def _pad(a, n, fill=0):
            if a.shape[0] == n:
                return a
            out = np.full((n,) + a.shape[1:], fill, dtype=a.dtype)
            out[: a.shape[0]] = a
            return out

        if self.per_device:
            self._seq = 0
            L_max = transport.allreduce_max(len(rows), "res-L-size")
            N_max = transport.allreduce_max(len(store), "res-N-size")
        else:
            L_max, N_max = len(rows), len(store)

        def place(a):
            if self.per_device:
                from paddlebox_tpu.parallel.mesh import put_per_device_copies

                return put_per_device_copies(plan, a)
            return jnp.asarray(a)

        self.rows = place(_pad(rows.astype(np.int32), L_max))
        # per-(record, slot) offsets into the flat row stream. Wire-compact
        # form: per-slot COUNTS fit uint8 (CTR slots hold a handful of
        # feasigns), so the upload ships [N, S] bytes + an [N] int32 base
        # instead of [N, S+1] int32 — ~4x less than the offset matrix, the
        # bulk of the resident upload after `rows`. Offsets rebuild on
        # device as a per-batch cumsum (batch_offsets). Falls back to the
        # full matrix when any slot exceeds 255 keys.
        slot_counts = np.diff(store.u64_offsets.astype(np.int64), axis=1)
        compact = slot_counts.size and slot_counts.max() <= 255
        if self.per_device:
            # lockstep the representation: one host falling back to the
            # offset matrix while another compresses would desync shapes
            compact = transport.allreduce_max(0 if compact else 1, "res-rep") == 0
        if compact:
            self.base = place(_pad(store.u64_base.astype(np.int32), N_max))
            self.counts = place(_pad(slot_counts.astype(np.uint8), N_max))
            self.off = None
        else:
            off = store.u64_base[:, None] + store.u64_offsets.astype(np.int64)
            self.base = None
            self.counts = None
            self.off = place(_pad(off.astype(np.int32), N_max))  # [N, S+1]
        label_name = label_slot or schema.label_slot
        if label_name is not None:
            li = schema.float_slot_index(label_name)
            labels = store.float_slot_matrix(li, 1)[:, 0]
        else:
            labels = np.zeros(len(store), np.float32)
        self.labels = place(_pad(labels.astype(np.float32), N_max))
        self.dense = None
        if dense_slot is not None and dense_dim:
            di = schema.float_slot_index(dense_slot)
            self.dense = place(
                _pad(
                    np.asarray(store.float_slot_matrix(di, dense_dim)), N_max
                )
            )
        self.L_pad = 0
        self.U_pad = 0
        self.K_pad = 0  # mesh tier: per-(device, shard) request bucket
        # keyed by the exact index bytes, not a hash — a collision would
        # freeze U_pad too small and silently merge distinct rows
        self._uniq_cache: Dict[bytes, int] = {}
        self._mesh_cache: Dict = {}  # (device, idx bytes) -> (L, bucket max)

    def ensure(self, batch_indices) -> None:
        """Freeze/grow L_pad and U_pad to cover every batch in the partition
        (exact per-batch max key and unique-row counts; results cached per
        index block so repeated passes over the same partition are free).
        Uncached blocks sweep in ONE native GIL-released call
        (pbx_block_stats with ns=1: total uniques) — the counter side of
        the reference's pass equalization (data_set.cc:2069-2135), keeping
        pass prepare off the Python critical path."""
        blocks = [np.asarray(idx) for idx in batch_indices]
        fps = [b.tobytes() for b in blocks]
        pending, seen = [], set()
        for b, fp in zip(blocks, fps):
            if fp not in self._uniq_cache and fp not in seen:
                pending.append((fp, b))
                seen.add(fp)
        if pending:
            stats = _native_pad_stats(
                self, [b for _, b in pending], self.n_table_rows, 1
            )
            if stats is not None:
                for (fp, _), U in zip(pending, stats[1]):
                    self._uniq_cache[fp] = max(int(U), 1)
            else:
                from paddlebox_tpu.data.record_store import _ragged_indices

                for fp, idx in pending:
                    base = self.store.u64_base[idx]
                    counts = self._key_counts[idx]
                    rows = self._host_rows[_ragged_indices(base, counts)]
                    self._uniq_cache[fp] = (
                        len(np.unique(rows)) if len(rows) else 1
                    )
        max_L, max_U = 1, 1
        for b, fp in zip(blocks, fps):
            max_L = max(max_L, int(self._key_counts[b].sum()))
            max_U = max(max_U, self._uniq_cache[fp])
        self.L_pad = max(self.L_pad, _round_bucket(max_L, self.bucket))
        # +1 keeps a dedicated slot for the invalid tail even when a batch
        # is exactly at the unique maximum
        self.U_pad = max(self.U_pad, _round_bucket(max_U + 1, self.bucket))



def _batch_offsets(arrs: Dict[str, jnp.ndarray], idx: jnp.ndarray) -> jnp.ndarray:
    """[B, S+1] absolute flat-stream offsets for a batch, from whichever
    resident representation was uploaded (full matrix, or base+uint8
    counts rebuilt by cumsum on device)."""
    if arrs.get("off") is not None:
        return arrs["off"][idx]
    c = arrs["counts"][idx].astype(jnp.int32)  # [B, S]
    cum = jnp.cumsum(c, axis=1)
    zero = jnp.zeros((cum.shape[0], 1), jnp.int32)
    return arrs["base"][idx][:, None] + jnp.concatenate([zero, cum], axis=1)


def _ragged_rows(
    rows_res: jnp.ndarray,
    off_b: jnp.ndarray,  # [B, S+1] this batch's absolute offsets
    S: int,
    B: int,
    L_pad: int,
    pad_value,
):
    """Shared ragged gather: batch offsets -> (rows_flat, segments, valid)
    in slot-major flat order. ``pad_value`` fills invalid tail rows (the
    single-device tier pads with the real padding row; the mesh tier with
    an out-of-range sentinel its sort treats as +inf)."""
    lens_b = off_b[:, 1:] - off_b[:, :-1]
    starts_b = off_b[:, :-1]
    lens_flat = lens_b.T.reshape(-1)  # [S*B] slot-major
    starts_flat = starts_b.T.reshape(-1)
    cum = jnp.cumsum(lens_flat)
    L_real = cum[-1]
    pos = jnp.arange(L_pad, dtype=jnp.int32)
    seg_c = jnp.minimum(
        jnp.searchsorted(cum, pos, side="right").astype(jnp.int32), S * B - 1
    )
    within = pos - (cum[seg_c] - lens_flat[seg_c])
    src = jnp.clip(starts_flat[seg_c] + within, 0, rows_res.shape[0] - 1)
    valid = pos < L_real
    rows_flat = jnp.where(valid, rows_res[src], pad_value)
    segments = jnp.where(valid, seg_c, S * B)  # seg_c IS slot*B + ins
    return rows_flat, segments, valid


def build_device_batch(
    rp: ResidentPass, cfg: TrainStepConfig, idx: jnp.ndarray
) -> Dict[str, jnp.ndarray]:
    """[B] record indices -> the classic step's batch dict, all on device.

    Produces the same arrays BatchPacker.pack ships from the host (slot-
    major flat order, pads -> padding row / U_pad-1 / S*B trash segment),
    so make_train_step's body consumes either source interchangeably.
    """
    S, B = cfg.num_slots, cfg.batch_size
    L_pad, U_pad = rp.L_pad, rp.U_pad
    off_b = _batch_offsets(
        {"off": rp.off, "base": rp.base, "counts": rp.counts}, idx
    )
    rows_flat, segments, valid = _ragged_rows(
        rp.rows, off_b, S, B, L_pad, rp.pad_row
    )
    # cross-slot dedup on device: sort rows, first-occurrence scan
    INF = jnp.int32(rp.n_table_rows)
    sort_keys = jnp.where(valid, rows_flat, INF)
    sorted_rows, perm = jax.lax.sort_key_val(
        sort_keys, jnp.arange(L_pad, dtype=jnp.int32)
    )
    real = sorted_rows < INF
    first = (
        jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sorted_rows[1:] != sorted_rows[:-1]]
        )
        & real
    )
    segid = jnp.minimum(jnp.cumsum(first.astype(jnp.int32)) - 1, U_pad - 1)
    segid = jnp.where(real, segid, U_pad - 1)
    uniq = jax.ops.segment_max(
        jnp.where(real, sorted_rows, -1), segid, num_segments=U_pad
    )
    uniq_rows = jnp.where(uniq >= 0, uniq, rp.pad_row).astype(jnp.int32)
    inverse = jnp.zeros((L_pad,), jnp.int32).at[perm].set(segid)
    batch = {
        "uniq_rows": uniq_rows,
        "inverse": inverse,
        "segments": segments,
        "labels": rp.labels[idx],
    }
    if rp.dense is not None:
        batch["dense"] = rp.dense[idx]
    return batch


def make_resident_superstep(
    model_apply: Callable,
    dense_opt,
    cfg: TrainStepConfig,
    rp: ResidentPass,
    eval_mode: bool = False,
) -> Callable:
    """Build ``superstep(state, idx_block [K, B]) -> (state, metrics[K])``.

    One dispatch runs K full train steps via lax.scan; metrics come back
    stacked along the scan axis. The per-step body is the classic
    make_train_step — only batch assembly is resident."""
    raw_step = make_train_step(model_apply, dense_opt, cfg, eval_mode=eval_mode)

    def body(state, idx):
        batch = build_device_batch(rp, cfg, idx)
        return raw_step(state, batch)

    def superstep(state, idx_block):
        return jax.lax.scan(body, state, idx_block)

    return jax.jit(superstep, donate_argnums=(0,))


# ---- resident pv (join-phase) tier -----------------------------------------


class ResidentPvFeed:
    """The pass's PvPlan uploaded to device HBM once.

    Join-phase batches are pass-deterministic after ``preprocess_instance``
    (PvPlan), so the per-batch feed shrinks to a [K] vector of BATCH
    POSITIONS — even smaller than the flat tier's [K, B] index feed. The
    jitted step gathers the batch's record indices, rank_offset, and
    ins_weight from these resident arrays (the reference keeps pv batches on
    the same MiniBatchGpuPack fast path as flat ones, data_feed.cc:2404-2522;
    here they additionally skip the host entirely).

    Mesh layout: idx/ro/w reshape to [n_b, n_dev, ...] and shard on the
    device axis, so each device stores and reads only its own block.
    """

    def __init__(self, plan, mesh_plan=None):
        idx = plan.idx.astype(np.int32)
        ro = plan.rank_offset
        w = plan.ins_weight
        self.n_batches = plan.n_batches
        if mesh_plan is None:
            self.idx = jnp.asarray(idx)  # [n_b, B]
            self.ro = jnp.asarray(ro)  # [n_b, B, R]
            self.w = jnp.asarray(w)  # [n_b, B]
        else:
            from paddlebox_tpu.parallel.mesh import put_axis1_blocks

            nd_local = mesh_plan.n_devices // jax.process_count()
            if plan.n_devices != nd_local:
                raise ValueError(
                    f"PvPlan built for {plan.n_devices} devices, this "
                    f"process packs for {nd_local}"
                )
            n_b, B = idx.shape
            b = B // nd_local

            def shard(a, *trail):
                # [n_b, n_local, b, ...] local blocks -> global
                # [n_b, n_dev, b, ...] sharded on the device axis
                # (single- and multi-host; hosts contribute their own
                # plans' blocks, n_b locksteped via min_batches)
                return put_axis1_blocks(
                    mesh_plan, a.reshape(n_b, nd_local, b, *trail)
                )

            self.idx = shard(idx)  # [n_b, n_dev, b]
            self.ro = shard(ro, ro.shape[-1])  # [n_b, n_dev, b, R]
            self.w = shard(w)  # [n_b, n_dev, b]


def make_resident_pv_superstep(
    model_apply: Callable,
    dense_opt,
    cfg: TrainStepConfig,
    rp: ResidentPass,
    feed: ResidentPvFeed,
    eval_mode: bool = False,
) -> Callable:
    """``superstep(state, pos_block [K]) -> (state, metrics[K])``: the pv
    analog of make_resident_superstep. Batch assembly reuses
    build_device_batch (ghosts are ordinary repeated records; their
    weight-0 rows add no loss, no show/clk, no AUC — same contract as the
    host-packed pv path)."""
    raw_step = make_train_step(model_apply, dense_opt, cfg, eval_mode=eval_mode)

    def body(state, pos):
        batch = build_device_batch(rp, cfg, feed.idx[pos])
        batch["ins_weight"] = feed.w[pos]
        batch["rank_offset"] = feed.ro[pos]
        return raw_step(state, batch)

    def superstep(state, pos_block):
        return jax.lax.scan(body, state, pos_block)

    return jax.jit(superstep, donate_argnums=(0,))


def make_resident_pv_mesh_superstep(
    model_apply: Callable,
    dense_opt,
    cfg: TrainStepConfig,
    rp: ResidentPass,
    feed: ResidentPvFeed,
    plan,
    eval_mode: bool = False,
) -> Callable:
    """Mesh pv superstep: ``superstep(state, pos_block [K])``.

    Single- AND multi-host: the pv arrays are device-axis sharded (each
    device holds its own [n_b, 1, b] block — on a multi-host mesh, of its
    own host's locksteped plan); the position feed is replicated (n_b is
    equalized via ghost batches). Per-device batch assembly and step body
    are shared with the flat mesh tier; multi-host additionally requires
    per-device resident pass arrays (rp.per_device)."""
    import jax as _jax

    from paddlebox_tpu.parallel.mesh import shard_map as _mesh_shard_map
    from jax.sharding import PartitionSpec as P

    from paddlebox_tpu.train.sharded_step import (
        make_local_mesh_step,
        mesh_metric_specs,
        mesh_state_specs,
    )

    if _jax.process_count() > 1 and not rp.per_device:
        raise RuntimeError(
            "multi-host resident pv feed needs per-device pass arrays — "
            "build the ResidentPass with plan= and a multi-rank transport="
        )
    local_step = make_local_mesh_step(model_apply, dense_opt, cfg, plan, eval_mode)
    ns, cap = rp.ws.n_mesh_shards, rp.ws.capacity
    L_pad, K = rp.L_pad, rp.K_pad
    rp_arrays = _resident_arrays(rp)
    per_device = rp.per_device

    def superstep_local(state, pos_block, arrs, pv_idx, pv_ro, pv_w):
        if per_device:  # multi-host: each device carries its host's arrays
            arrs = {k: v[0] for k, v in arrs.items()}

        def body(st, pos):
            batch = build_mesh_device_batch(
                arrs, cfg, pv_idx[pos, 0], L_pad, K, ns, cap
            )
            batch = {k: v[None] for k, v in batch.items()}
            batch["ins_weight"] = pv_w[pos]  # [1, b] local block
            batch["rank_offset"] = pv_ro[pos]  # [1, b, R]
            return local_step(st, batch)

        return _jax.lax.scan(body, state, pos_block)

    state_specs = mesh_state_specs(cfg, dense_opt, plan)
    per_step = mesh_metric_specs(cfg, plan, eval_mode)
    metric_specs = {
        k: (P(None, *s) if s else P()) for k, s in per_step.items()
    }
    rep = P()
    ax = plan.axis
    arr_specs = {k: (P(ax) if per_device else P()) for k in rp_arrays}

    def superstep(state, pos_block, arrs, pv_idx, pv_ro, pv_w):
        mapped = _mesh_shard_map(
            superstep_local,
            mesh=plan.mesh,
            in_specs=(
                state_specs,
                rep,  # batch positions: replicated
                arr_specs,  # replicated, or per-device host copies
                P(None, ax, None),  # pv_idx [n_b, n_dev, b]
                P(None, ax, None, None),  # pv_ro
                P(None, ax, None),  # pv_w
            ),
            out_specs=(state_specs, metric_specs),
            check_vma=False,
        )
        return mapped(state, pos_block, arrs, pv_idx, pv_ro, pv_w)

    jitted = _jax.jit(superstep, donate_argnums=(0,))

    def call(state, pos_block):
        # multi-host arrays must be jit ARGUMENTS, not closure constants
        return jitted(state, pos_block, rp_arrays, feed.idx, feed.ro, feed.w)

    return call


# ---- mesh (single-host) resident tier --------------------------------------


def _native_pad_stats(rp: ResidentPass, slices, cap: int, ns: int):
    """One GIL-released pbx_block_stats sweep over equal-length index
    slices -> (L[n], bmax[n]), or None when the native tier is absent or
    the slices are ragged (caller falls back to the per-block numpy
    sweep)."""
    from paddlebox_tpu.utils import native

    if not native.available() or not slices:
        return None
    if len({len(s) for s in slices}) != 1:
        return None
    blocks = np.stack([np.asarray(s, dtype=np.int64) for s in slices])
    return native.block_stats(
        rp._host_rows, rp.store.u64_base, rp._key_counts, blocks, cap, ns
    )


def ensure_sharded(rp: ResidentPass, batch_indices, n_devices: int) -> None:
    """Freeze/grow the mesh pads: per-DEVICE L_pad and the per-(device,
    shard) request bucket K_pad (exact scan, cached per index block — the
    resident analog of BatchPacker.freeze_shapes' lockstep branch).
    ``n_devices`` is the count THIS process packs for (local on a
    multi-host mesh); with a multi-rank transport on the ResidentPass the
    pads are allreduce-max'd so every host compiles the same program.
    Uncached device blocks sweep in ONE native call (pbx_block_stats) —
    pass prepare is one native counter sweep + one allreduce, the
    reference's equalization shape (data_set.cc:2069-2135)."""
    cap, ns = rp.ws.capacity, rp.ws.n_mesh_shards
    work = []  # (fp, slice) per device block, cache-order
    pending, seen = [], set()
    for idx in batch_indices:
        idx = np.asarray(idx)
        if len(idx) % n_devices:
            raise ValueError(
                f"batch of {len(idx)} records not divisible by "
                f"{n_devices} devices (same contract as the host packer)"
            )
        b = len(idx) // n_devices
        for d in range(n_devices):
            sl = idx[d * b : (d + 1) * b]
            fp = (d, sl.tobytes())
            work.append(fp)
            if fp not in rp._mesh_cache and fp not in seen:
                pending.append((fp, sl))
                seen.add(fp)
    if pending:
        stats = _native_pad_stats(rp, [s for _, s in pending], cap, ns)
        if stats is not None:
            for (fp, _), L, bm in zip(pending, stats[0], stats[1]):
                rp._mesh_cache[fp] = (int(L), int(bm))
        else:
            from paddlebox_tpu.data.record_store import _ragged_indices

            for fp, sl in pending:
                counts = rp._key_counts[sl]
                rows = rp._host_rows[
                    _ragged_indices(rp.store.u64_base[sl], counts)
                ]
                L = len(rows)
                if L:
                    uniq = np.unique(rows)
                    bmax = int(np.bincount(uniq // cap, minlength=ns).max())
                else:
                    bmax = 0
                rp._mesh_cache[fp] = (L, bmax)
    max_L, max_bucket = 1, 0
    for fp in work:
        cached = rp._mesh_cache[fp]
        max_L = max(max_L, cached[0])
        max_bucket = max(max_bucket, cached[1])
    L = _round_bucket(max_L, rp.bucket)
    K = _round_bucket(max_bucket + 1, rp.bucket)
    tp = rp.transport
    if tp is not None and tp.n_ranks > 1:
        # lockstep: every host enters these collectives the same number of
        # times (the stepper/prepare call sequence is uniform), tagged by a
        # per-ResidentPass counter
        rp._seq += 1
        L = tp.allreduce_max(L, f"res-L:{rp._seq}")
        K = tp.allreduce_max(K, f"res-K:{rp._seq}")
    rp.L_pad = max(rp.L_pad, L)
    rp.K_pad = max(rp.K_pad, K)


def build_mesh_device_batch(
    rp_arrays: Dict[str, jnp.ndarray],
    cfg: TrainStepConfig,
    idx_dev: jnp.ndarray,  # [b] this device's record indices
    L_pad: int,
    K: int,
    ns: int,
    cap: int,
) -> Dict[str, jnp.ndarray]:
    """One device's mesh batch (req_ranks/inverse/segments/labels) built on
    device from the resident arrays — the _route_sharded host routine as
    static-shape XLA ops (sort groups rows by owner shard for free since
    global row ids are shard-major: row = shard*cap + rank)."""
    S, b = cfg.num_slots, cfg.batch_size
    rows_res, labels_res = rp_arrays["rows"], rp_arrays["labels"]
    off_b = _batch_offsets(rp_arrays, idx_dev)
    rows_flat, segments, valid = _ragged_rows(
        rows_res, off_b, S, b, L_pad, jnp.int32(ns * cap)
    )

    # route: sort by global row id (== by owner shard), first-occurrence
    # scan assigns each unique row its request-bucket slot j within its
    # shard; pads ride in bucket (shard 0, K-1), whose row is the reserved
    # padding row cap-1 via the req_ranks fill
    INF = jnp.int32(ns * cap)  # rows_flat already pads with this sentinel
    sorted_rows, perm = jax.lax.sort_key_val(
        rows_flat, jnp.arange(L_pad, dtype=jnp.int32)
    )
    real = sorted_rows < INF
    first = (
        jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sorted_rows[1:] != sorted_rows[:-1]]
        )
        & real
    )
    uniq_seq = jnp.cumsum(first.astype(jnp.int32)) - 1  # global unique ordinal
    shard = jnp.where(real, sorted_rows // cap, 0)
    cnts = jax.ops.segment_sum(
        first.astype(jnp.int32), shard, num_segments=ns
    )  # uniques per shard
    shard_start = jnp.cumsum(cnts) - cnts  # exclusive
    j = jnp.clip(uniq_seq - shard_start[shard], 0, K - 2)
    bucket_sorted = jnp.where(real, shard * K + j, (K - 1))  # pads -> shard 0
    inverse = jnp.zeros((L_pad,), jnp.int32).at[perm].set(bucket_sorted)
    # request matrix: rank-within-shard at (shard, j) for each first
    # occurrence; everything else (incl. the K-1 pad slot) = cap-1 pad row
    flat_pos = jnp.where(first, shard * K + j, ns * K)  # non-first -> dropped
    req_ranks = (
        jnp.full((ns * K,), cap - 1, jnp.int32)
        .at[flat_pos]
        .set(jnp.where(real, sorted_rows % cap, cap - 1).astype(jnp.int32),
             mode="drop")
        .reshape(ns, K)
    )
    out = {
        "req_ranks": req_ranks,
        "inverse": inverse,
        "segments": segments,
        "labels": labels_res[idx_dev],
    }
    if "dense" in rp_arrays:
        out["dense"] = rp_arrays["dense"][idx_dev]
    return out


def make_resident_mesh_superstep(
    model_apply: Callable,
    dense_opt,
    cfg: TrainStepConfig,
    rp: ResidentPass,
    plan,
    eval_mode: bool = False,
) -> Callable:
    """``superstep(state, idx_block [K_scan, n_dev, b]) -> (state, metrics)``
    on a SINGLE-HOST mesh: resident arrays replicated across local devices,
    each device builds its own route buckets, then the shared per-device
    mesh step body runs (make_local_mesh_step — identical numerics to the
    host-packed path)."""
    import jax as _jax

    from paddlebox_tpu.parallel.mesh import shard_map as _mesh_shard_map
    from jax.sharding import PartitionSpec as P

    from paddlebox_tpu.train.sharded_step import (
        make_local_mesh_step,
        mesh_metric_specs,
        mesh_state_specs,
    )

    if _jax.process_count() > 1 and not rp.per_device:
        raise RuntimeError(
            "multi-host resident feed needs per-device pass arrays — build "
            "the ResidentPass with plan= and a multi-rank transport="
        )
    local_step = make_local_mesh_step(model_apply, dense_opt, cfg, plan, eval_mode)
    ns, cap = rp.ws.n_mesh_shards, rp.ws.capacity
    L_pad, K = rp.L_pad, rp.K_pad

    rp_arrays = _resident_arrays(rp)
    per_device = rp.per_device

    def superstep_local(state, idx_block, arrs):
        if per_device:  # each device carries its host's copy: strip [1,...]
            arrs = {k: v[0] for k, v in arrs.items()}

        def body(st, idx):  # idx [1, b] (this device's slice)
            batch = build_mesh_device_batch(
                arrs, cfg, idx[0], L_pad, K, ns, cap
            )
            batch = {k: v[None] for k, v in batch.items()}
            return local_step(st, batch)

        return _jax.lax.scan(body, state, idx_block)

    state_specs = mesh_state_specs(cfg, dense_opt, plan)
    # per-step metric specs shift one dim right under the scan stacking:
    # preds/labels come out [K_scan, b] per device and assemble
    # [K_scan, n_dev*b] — P(axis) on dim 0 would interleave devices into
    # the scan axis and hand consumers only device 0's slice
    per_step = mesh_metric_specs(cfg, plan, eval_mode)
    metric_specs = {
        k: (P(None, *s) if s else P()) for k, s in per_step.items()
    }

    arr_specs = {
        k: (P(plan.axis) if per_device else P()) for k in rp_arrays
    }

    def superstep(state, idx_block, arrs):
        mapped = _mesh_shard_map(
            superstep_local,
            mesh=plan.mesh,
            in_specs=(
                state_specs,
                P(None, plan.axis),  # scan axis whole, device axis split
                arr_specs,  # replicated, or per-device host copies
            ),
            out_specs=(state_specs, metric_specs),
            check_vma=False,
        )
        return mapped(state, idx_block, arrs)

    jitted = _jax.jit(superstep, donate_argnums=(0,))

    def call(state, idx_block):
        # multi-host arrays span non-addressable devices: they must enter
        # the jit as ARGUMENTS, not closure constants
        return jitted(state, idx_block, rp_arrays)

    return call


def _resident_arrays(rp: ResidentPass) -> Dict[str, jnp.ndarray]:
    """The resident arrays a mesh superstep threads through shard_map —
    only the representation that was actually uploaded (off matrix, or
    base+counts), plus optional dense features."""
    arrs = {"rows": rp.rows, "labels": rp.labels}
    if rp.off is not None:
        arrs["off"] = rp.off
    else:
        arrs["base"] = rp.base
        arrs["counts"] = rp.counts
    if rp.dense is not None:
        arrs["dense"] = rp.dense
    return arrs
