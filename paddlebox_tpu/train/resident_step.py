"""Device-resident pass feed: upload the pass once, feed only indices.

The classic feed path ships ~10 bytes/key/batch (uniq_rows + inverse +
segments) from host to device every batch — the MiniBatchGpuPack H2D copy
(data_feed.h:1492-1504), fine over PCIe, dominant over a bandwidth-limited
host<->TPU transport. This path exploits what the reference cannot: the
whole pass is immutable once `begin_pass` runs (PadBoxSlotDataset keeps
`input_records_` frozen for the pass, data_set.cc:1628-1683), so the
row-resolved key stream can live in device HBM for the pass:

- **Upload once per pass**: flat row ids for every key of every record
  (`rows`), per-record per-slot absolute offsets (`off`), labels, optional
  dense features. ~8 bytes/key, once.
- **Per batch**: feed is ONE [B] int32 record-index vector (~16 KB). The
  jitted step rebuilds the batch on device: ragged gather via
  cumsum+searchsorted, then cross-slot dedup via sort + segment scan
  (DedupKeysAndFillIdx parity, box_wrapper_impl.h:103 — the reference runs
  the same dedup as a device kernel, not on the host).
- **Superstep**: `lax.scan` over K batches per dispatch amortizes the
  host->device dispatch round-trip (BoxPSWorker's batch loop
  boxps_worker.cc:420-466 collapses into one XLA program per K batches).

The produced per-batch arrays are bit-compatible with BatchPacker.pack
(same slot-major flat order, same padding conventions), and the train-step
body is REUSED from train_step.make_train_step — the resident tier changes
where the batch is assembled, never what the step computes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.data.device_pack import _round_bucket
from paddlebox_tpu.train.train_step import TrainStepConfig, make_train_step

config.define_flag(
    "enable_resident_feed",
    1,
    "keep the pass's row stream resident in device HBM and feed only "
    "record indices per batch (single-device fast path; 0 = classic "
    "per-batch host packing)",
)
config.define_flag(
    "resident_scan_batches",
    8,
    "minibatches per dispatched superstep (lax.scan length); higher "
    "amortizes dispatch latency, lower returns metrics sooner",
)


class ResidentPass:
    """Pass-scoped device arrays + static pad shapes for the resident feed.

    Built once per (store, working set); ~8 bytes/key of HBM. ``ensure``
    grows the frozen pad shapes to cover a batch partition (sticky, like
    BatchPacker.freeze_shapes — one compiled program per pass).
    """

    def __init__(
        self,
        store,  # ColumnarRecords
        ws,  # PassWorkingSet (finalized)
        schema,
        dense_slot: Optional[str] = None,
        dense_dim: int = 0,
        label_slot: Optional[str] = None,
        bucket: Optional[int] = None,
    ):
        self.store = store
        self.ws = ws
        self.num_slots = store.n_sparse
        self.bucket = bucket or config.get_flag("batch_bucket_rounding")
        self.n_table_rows = ws.n_mesh_shards * ws.capacity
        self.pad_row = self.n_table_rows - 1
        rows = store.resolve_rows(ws)
        if len(store.u64_values) >= (1 << 31):  # int32 src indexing
            raise ValueError("pass too large for resident feed (>=2^31 keys)")
        self._host_rows = rows
        self._key_counts = store.key_counts()
        # absolute per-(record, slot) offsets into the flat row stream
        off = store.u64_base[:, None] + store.u64_offsets.astype(np.int64)
        self.rows = jnp.asarray(rows.astype(np.int32))
        self.off = jnp.asarray(off.astype(np.int32))  # [N, S+1]
        label_name = label_slot or schema.label_slot
        if label_name is not None:
            li = schema.float_slot_index(label_name)
            labels = store.float_slot_matrix(li, 1)[:, 0]
        else:
            labels = np.zeros(len(store), np.float32)
        self.labels = jnp.asarray(labels.astype(np.float32))
        self.dense = None
        if dense_slot is not None and dense_dim:
            di = schema.float_slot_index(dense_slot)
            self.dense = jnp.asarray(store.float_slot_matrix(di, dense_dim))
        self.L_pad = 0
        self.U_pad = 0
        # keyed by the exact index bytes, not a hash — a collision would
        # freeze U_pad too small and silently merge distinct rows
        self._uniq_cache: Dict[bytes, int] = {}

    def ensure(self, batch_indices) -> None:
        """Freeze/grow L_pad and U_pad to cover every batch in the partition
        (exact per-batch max key and unique-row counts; results cached per
        index block so repeated passes over the same partition are free)."""
        max_L, max_U = 1, 1
        for idx in batch_indices:
            idx = np.asarray(idx)
            max_L = max(max_L, int(self._key_counts[idx].sum()))
            fp = idx.tobytes()
            n_uniq = self._uniq_cache.get(fp)
            if n_uniq is None:
                from paddlebox_tpu.data.record_store import _ragged_indices

                base = self.store.u64_base[idx]
                counts = self._key_counts[idx]
                rows = self._host_rows[_ragged_indices(base, counts)]
                n_uniq = len(np.unique(rows)) if len(rows) else 1
                self._uniq_cache[fp] = n_uniq
            max_U = max(max_U, n_uniq)
        self.L_pad = max(self.L_pad, _round_bucket(max_L, self.bucket))
        # +1 keeps a dedicated slot for the invalid tail even when a batch
        # is exactly at the unique maximum
        self.U_pad = max(self.U_pad, _round_bucket(max_U + 1, self.bucket))



def build_device_batch(
    rp: ResidentPass, cfg: TrainStepConfig, idx: jnp.ndarray
) -> Dict[str, jnp.ndarray]:
    """[B] record indices -> the classic step's batch dict, all on device.

    Produces the same arrays BatchPacker.pack ships from the host (slot-
    major flat order, pads -> padding row / U_pad-1 / S*B trash segment),
    so make_train_step's body consumes either source interchangeably.
    """
    S, B = cfg.num_slots, cfg.batch_size
    L_pad, U_pad = rp.L_pad, rp.U_pad
    off_b = rp.off[idx]  # [B, S+1]
    lens_b = off_b[:, 1:] - off_b[:, :-1]
    starts_b = off_b[:, :-1]
    # slot-major flat order: all instances' slot-0 keys, then slot 1 ...
    lens_flat = lens_b.T.reshape(-1)  # [S*B]
    starts_flat = starts_b.T.reshape(-1)
    cum = jnp.cumsum(lens_flat)
    L_real = cum[-1]
    pos = jnp.arange(L_pad, dtype=jnp.int32)
    seg_c = jnp.minimum(
        jnp.searchsorted(cum, pos, side="right").astype(jnp.int32), S * B - 1
    )
    within = pos - (cum[seg_c] - lens_flat[seg_c])
    src = jnp.clip(starts_flat[seg_c] + within, 0, rp.rows.shape[0] - 1)
    valid = pos < L_real
    rows_flat = jnp.where(valid, rp.rows[src], rp.pad_row)
    segments = jnp.where(valid, seg_c, S * B)  # seg_c IS slot*B + ins
    # cross-slot dedup on device: sort rows, first-occurrence scan
    INF = jnp.int32(rp.n_table_rows)
    sort_keys = jnp.where(valid, rows_flat, INF)
    sorted_rows, perm = jax.lax.sort_key_val(
        sort_keys, jnp.arange(L_pad, dtype=jnp.int32)
    )
    real = sorted_rows < INF
    first = (
        jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sorted_rows[1:] != sorted_rows[:-1]]
        )
        & real
    )
    segid = jnp.minimum(jnp.cumsum(first.astype(jnp.int32)) - 1, U_pad - 1)
    segid = jnp.where(real, segid, U_pad - 1)
    uniq = jax.ops.segment_max(
        jnp.where(real, sorted_rows, -1), segid, num_segments=U_pad
    )
    uniq_rows = jnp.where(uniq >= 0, uniq, rp.pad_row).astype(jnp.int32)
    inverse = jnp.zeros((L_pad,), jnp.int32).at[perm].set(segid)
    batch = {
        "uniq_rows": uniq_rows,
        "inverse": inverse,
        "segments": segments,
        "labels": rp.labels[idx],
    }
    if rp.dense is not None:
        batch["dense"] = rp.dense[idx]
    return batch


def make_resident_superstep(
    model_apply: Callable,
    dense_opt,
    cfg: TrainStepConfig,
    rp: ResidentPass,
    eval_mode: bool = False,
) -> Callable:
    """Build ``superstep(state, idx_block [K, B]) -> (state, metrics[K])``.

    One dispatch runs K full train steps via lax.scan; metrics come back
    stacked along the scan axis. The per-step body is the classic
    make_train_step — only batch assembly is resident."""
    raw_step = make_train_step(model_apply, dense_opt, cfg, eval_mode=eval_mode)

    def body(state, idx):
        batch = build_device_batch(rp, cfg, idx)
        return raw_step(state, batch)

    def superstep(state, idx_block):
        return jax.lax.scan(body, state, idx_block)

    return jax.jit(superstep, donate_argnums=(0,))
