"""Confirm/revert pass rollback (FleetWrapper::Confirm/Revert parity).

The reference exposes pass-grained rollback on its PS tables
(fleet_wrapper.h:319-321; pslib __init__.py:673-690: "confirm the updated
params" / "revert ... to the previous saved state"): a pass whose output is
rejected (bad data, poisoned gradients, failed validation) is rolled back
so the table re-enters the state it had when the pass began.

TPU shape of the same contract: a pass mutates exactly
- the working set's keys in the host table (end_pass writeback; keys
  created by finalize get deterministic per-key init values, so restoring
  their pre-train rows makes retraining bit-reproducible), and
- the trainer's dense params/optimizer state.

``PassGuard.begin`` snapshots both right after ``begin_pass`` builds the
working set; ``revert`` pushes the snapshot back (undoing any partial or
complete writeback) and restores the dense side; ``confirm`` drops the
snapshot. end_pass's decay/shrink runs AFTER writeback, so the
begin->revert window covers everything a rejected pass could have
published; crash-recovery across decay itself is the CheckpointManager's
(day-level) job, not revert's.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np


class PassGuard:
    """Snapshot-at-begin / revert-or-confirm for one training pass."""

    def __init__(self, table, trainer: Optional[Any] = None):
        self.table = table
        self.trainer = trainer
        # confirm() runs on the end_pass worker; revert() only after
        # wait_end_pass joins that worker (revert_pass waits first), so
        # the Future handoff is the happens-before edge
        self._keys: Optional[np.ndarray] = None  # synchronized-by: end-pass join handoff (wait_end_pass)
        self._vals: Optional[np.ndarray] = None  # synchronized-by: end-pass join handoff (wait_end_pass)
        self._dense: Optional[tuple] = None  # synchronized-by: end-pass join handoff (wait_end_pass)

    @property
    def armed(self) -> bool:
        return self._keys is not None

    def begin(self, pass_keys: np.ndarray) -> None:
        """Snapshot the pre-train rows of this pass's keys (call right
        after the working set is finalized) + the trainer's dense state."""
        self._keys = np.asarray(pass_keys, dtype=np.uint64).copy()
        self._vals = self.table.pull_or_create(self._keys).copy()
        if self.trainer is not None and self.trainer.params is not None:
            leaves, treedef = jax.tree.flatten(
                (self.trainer.params, self.trainer.opt_state)
            )
            self._dense = ([np.asarray(x).copy() for x in leaves], treedef)

    def confirm(self) -> None:
        """Accept the pass: drop the snapshot (Confirm parity)."""
        self._keys = self._vals = self._dense = None

    def revert(self) -> None:
        """Restore every pass key's pre-pass row and the dense state
        (Revert parity). Safe after zero, partial, or full writeback."""
        if self._keys is None:
            raise RuntimeError("no armed snapshot — begin() a pass first")
        if len(self._keys):
            self.table.push(self._keys, self._vals)
        if self._dense is not None and self.trainer is not None:
            leaves, treedef = self._dense
            self.trainer.params, self.trainer.opt_state = jax.tree.unflatten(
                treedef, [np.asarray(x) for x in leaves]
            )
            # the device-side state cache is stale now
            self.trainer._state = None
            self.trainer._state_ws = None
        self.confirm()
