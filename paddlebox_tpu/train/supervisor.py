"""PassSupervisor: the self-healing pass/day loop.

The repo has had the recovery *pieces* for a while — PassGuard
confirm/revert (train/rollback.py, FleetWrapper::Confirm/Revert parity),
retry-until-open on flaky inputs (utils/fs.py, data_feed.cc:2738-2740
parity), NaN-batch containment in the device step, and day-level
base+delta resume (train/checkpoint.py). What production actually needs is
the layer that COMPOSES them: a multi-day CTR run survives a bad pass
because something notices, reverts, retries, and — when retries don't help
— falls back to the last durable state and re-enters. That layer is
``PassSupervisor``.

One supervised pass runs:

    load (fs retries inside) -> begin_pass(enable_revert) [guard armed]
      -> prepare_pass -> train_pass -> health gates -> end_pass [confirm]
      -> optional checkpoint publish (base/delta, manifest-verified)

Any exception or gate rejection reverts the pass (bit-exact: retraining
after revert equals a never-interrupted run, pinned by
tests/test_rollback.py) and retries under bounded exponential backoff.
When ``max_retries`` is exhausted the supervisor escalates once: it
restores the last durable checkpoint state via ``CheckpointManager.
resume()`` (manifest-verified, torn-snapshot fallback) and re-enters with
a fresh retry budget. Every action lands in a structured incident log —
``self.incidents``, process-wide counters in utils/monitor, and instant
events in the utils/trace timeline.

Health gates (the "pass is poisoned" detectors the reference applies by
operator convention):

- NaN gate: the ratio of NaN-skipped batches (the step's containment
  counter) must stay under ``nan_ratio_max`` — a pass that skims over too
  many poisoned batches is itself poisoned.
- AUC floor: the pass AUC must not fall more than ``auc_floor_margin``
  below the trailing mean of the last ``auc_window`` CONFIRMED passes
  (only consulted after ``auc_min_history`` confirmations, so a cold
  start can't self-reject).

Poison awareness: corruption is NOT a transient fault. A load that
quarantined data beyond the admission thresholds (data/quarantine.py)
surfaces as ``DataPoisonedError`` — deterministic, because retrying the
same filelist replays the same corruption — so the supervisor resolves it
BEFORE the retry loop, without burning a single backoff retry, under the
``on_poisoned_pass`` policy: ``fail`` (raise, with a ``data_poisoned``
incident naming the dead-letter file), ``skip_pass`` (drop the pass's
data, keep the day), or ``degrade`` (train the pass with the quarantined
records dropped; the loss fraction lands in the incident and the pass
metrics). In coordinated runs the corrupt-fraction verdict rides the
same allgather as the pass/load verdicts, so every rank admits or
rejects in lockstep.

Distributed coordination (``transport=`` + :class:`EpochCoordinator`):
when the supervisor drives one rank of a multi-host run, a pass must
commit or revert GLOBALLY — one rank confirming a pass its peer reverted
leaves the host tables permanently diverged. So before ``end_pass`` every
rank publishes a verdict (my gates passed / my attempt raised) on a
control tag scoped by the current pass epoch; any NO — including a peer
that simply stopped answering, which times out the exchange — turns into
a :class:`CoordinatedAbort` on the healthy ranks, and every rank walks
the same revert path, bumps the same pass epoch (stale frames of the
aborted attempt are discarded by tag), and retries in lockstep. The
retried pass then runs over exactly the data + table state a clean run
would see, so its result is bitwise-equal to a never-faulted run
(tests/test_chaos_dist.py). Load failures coordinate the same way before
anything is armed. Escalation stays lockstep for free: verdicts are
global, every rank exhausts the same retry budget on the same attempt.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.data.quarantine import DataPoisonedError
from paddlebox_tpu.obs.flight_recorder import FLIGHT_RECORDER
from paddlebox_tpu.obs.metrics_writer import MetricsWriter
from paddlebox_tpu.parallel import membership as _membership
from paddlebox_tpu.parallel.transport import PeerDeadError
from paddlebox_tpu.train.checkpoint import MembershipEpochError
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE, STAT_SET
from paddlebox_tpu.utils.trace import PROFILER

# incident kinds that end a pass (or the day) rather than healing in
# place: each one flushes the flight recorder into an incident bundle
_FATAL_INCIDENT_KINDS = ("data_poisoned", "peer_abort", "gave_up")

config.define_flag(
    "supervisor_max_retries",
    2,
    "revert+retry attempts per pass before the supervisor escalates to a "
    "checkpoint resume (and, failing that, gives up)",
)
config.define_flag(
    "on_poisoned_pass",
    "fail",
    "supervisor policy when a pass's load quarantined data beyond the "
    "admission thresholds (DataPoisonedError — deterministic, never "
    "retried): 'fail' raises, 'skip_pass' drops the pass and continues "
    "the day, 'degrade' trains over the pass with the quarantined "
    "records dropped (loss fraction recorded in the incident and the "
    "pass metrics)",
)


class PassRejected(RuntimeError):
    """A health gate rejected an otherwise-completed pass."""

    def __init__(self, gate: str, detail: str):
        super().__init__(f"pass rejected by {gate} gate: {detail}")
        self.gate = gate
        self.detail = detail


class PassFailure(RuntimeError):
    """The supervisor exhausted retries AND escalation for one pass."""


class CoordinatedAbort(RuntimeError):
    """A peer rank voted NO on this pass (its gate fired or its attempt
    raised), or the verdict exchange itself failed — this rank's locally
    healthy attempt must revert so the cluster retries in lockstep."""

    def __init__(self, detail: str):
        super().__init__(f"pass aborted by peer verdict: {detail}")
        self.detail = detail


class EpochCoordinator:
    """Control-plane verdict exchange + pass-epoch bookkeeping for one rank.

    ``exchange_verdict`` is an allgather on tag ``ctl:verdict:<key>@e<N>``
    (payload ``b"\\x01"`` = ok, ``b"\\x00" + detail`` = abort): it returns
    the GLOBAL verdict, and treats its own transport failure/timeout as an
    abort vote — a rank that cannot hear its peers must not confirm.
    ``advance`` bumps the epoch after a revert and raises the transport's
    stale-frame floor, so nothing a reverted attempt left in flight can
    reach the retried attempt's exchanges (the epoch suffix is the same
    ``@e<N>`` convention DistributedWorkingSet tags carry)."""

    def __init__(self, transport, timeout: Optional[float] = None):
        self.transport = transport
        self.timeout = timeout
        self.epoch = 0
        # elastic mode re-raises PeerDeadError instead of folding it into
        # an abort vote: a dead peer is a MEMBERSHIP event (verdict round,
        # ownership shrink, adoption), not a retryable pass failure — the
        # supervisor's death handler owns it. Off by default so
        # non-elastic runs keep the historical fail-as-abort behavior.
        self.raise_peer_dead = False

    def exchange_verdict(
        self, key: str, ok: bool, detail: str = "", fatal: bool = False
    ):
        """Returns (global_ok, detail) after every rank has voted.

        ``fatal=True`` re-raises a LOCAL transport failure/timeout instead
        of folding it into a NO vote. A commit-point exchange (the migrate
        epoch flip) must use it: a rank that times out cannot tell whether
        its peers committed, and quietly voting NO while they did leaves
        this rank serving the old map against their new one — split-brain
        the epoch integer can't detect. Better to die loudly and be shrunk
        out by the survivors."""
        payload = b"\x01" if ok else b"\x00" + detail.encode()[:512]
        tag = f"ctl:verdict:{key}@e{self.epoch}"
        try:
            votes = self.transport.allgather(payload, tag, timeout=self.timeout)
        except PeerDeadError as e:
            if self.raise_peer_dead:
                raise
            STAT_ADD("supervisor_verdict_exchange_errors")
            return False, f"verdict exchange failed: {e!r}"
        except (OSError, TimeoutError) as e:
            STAT_ADD("supervisor_verdict_exchange_errors")
            if fatal:
                raise
            return False, f"verdict exchange failed: {e!r}"
        # membership-confirmed dead ranks contribute b"" placeholder slots,
        # not NO votes
        live_fn = getattr(self.transport, "live_ranks", None)
        live = set(live_fn()) if live_fn is not None else set(
            range(self.transport.n_ranks)
        )
        bad = [
            f"rank {r}: {v[1:].decode(errors='replace') or 'aborted'}"
            for r, v in enumerate(votes)
            if r in live and v[:1] != b"\x01"
        ]
        if bad:
            return False, "; ".join(bad)
        return True, ""

    def advance(self, epoch: Optional[int] = None) -> None:
        """Enter the next pass epoch (or adopt the dataset's counter, which
        revert_pass bumps — keeping the two in lockstep)."""
        self.epoch = self.epoch + 1 if epoch is None else epoch
        self.transport.discard_epochs_below(self.epoch)


@dataclass
class ElasticConfig:
    """Opt-in elastic membership for a coordinated supervisor.

    ``shared_root`` is the day root every rank publishes its checkpoint
    tree under (``rank-<r>`` subdirs, checkpoint.rank_root): the adoption
    path opens a DEAD rank's tree read-only through it. ``migrate_skew``
    > 1.0 additionally arms planned migration: at a confirmed pass
    boundary, when the max/mean per-rank key-load ratio crosses it, the
    supervisor recuts ownership boundaries and streams the moving ranges
    (see docs/ROBUSTNESS.md, "Elastic membership & key migration")."""

    shared_root: str
    migrate_skew: float = 0.0  # <= 1.0 disables planned migration
    adopt_retries: int = 2
    member_timeout: Optional[float] = None


@dataclass
class HealthGates:
    nan_ratio_max: float = 0.05
    auc_window: int = 5
    auc_min_history: int = 3
    auc_floor_margin: float = 0.05
    auc_absolute_floor: Optional[float] = None


@dataclass
class RetryPolicy:
    max_retries: Optional[int] = None  # None -> supervisor_max_retries flag
    backoff_s: float = 0.5
    backoff_mult: float = 2.0
    backoff_max_s: float = 30.0
    # injectable for tests (chaos schedules must not wall-clock sleep)
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    @property
    def retries(self) -> int:
        if self.max_retries is not None:
            return self.max_retries
        return int(config.get_flag("supervisor_max_retries"))

    def backoff(self, attempt: int) -> float:
        return min(
            self.backoff_s * self.backoff_mult ** max(0, attempt - 1),
            self.backoff_max_s,
        )


@dataclass
class Incident:
    """One structured entry of the supervisor's incident log."""

    pass_seq: int
    date: Optional[str]
    kind: str      # load_error | train_error | gate_nan | gate_auc |
                   # prefetch_error | ckpt_save_error | escalate_resume |
                   # gave_up | skipped | peer_abort | data_poisoned |
                   # rank_death | migrate | migrate_abort
    action: str    # retry | revert_retry | resume | raise | skip
    attempt: int
    detail: str = ""
    wall_time: float = field(default_factory=time.time)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "pass_seq": self.pass_seq,
            "date": self.date,
            "kind": self.kind,
            "action": self.action,
            "attempt": self.attempt,
            "detail": self.detail,
            "wall_time": self.wall_time,
        }


class PassSupervisor:
    """Fault-tolerant driver for the pass/day loop of one trainer.

    ``checkpoint`` (a CheckpointManager) enables both the escalation path
    and the per-pass publishing ``run_day`` performs; without it the
    supervisor still reverts/retries but gives up when retries exhaust.
    """

    def __init__(
        self,
        dataset,
        trainer,
        checkpoint=None,
        gates: Optional[HealthGates] = None,
        retry: Optional[RetryPolicy] = None,
        round_to: int = 512,
        shrink: bool = True,
        on_give_up: str = "raise",  # raise | skip (drop the pass, keep the day)
        transport=None,
        on_poisoned: Optional[str] = None,  # None -> on_poisoned_pass flag
        elastic: Optional[ElasticConfig] = None,
    ):
        if on_give_up not in ("raise", "skip"):
            raise ValueError(f"on_give_up must be 'raise' or 'skip', got {on_give_up!r}")
        if on_poisoned not in (None, "fail", "skip_pass", "degrade"):
            raise ValueError(
                "on_poisoned must be None, 'fail', 'skip_pass' or "
                f"'degrade', got {on_poisoned!r}"
            )
        self.ds = dataset
        self.tr = trainer
        self.table = dataset.table
        self.checkpoint = checkpoint
        self.gates = gates or HealthGates()
        self.retry = retry or RetryPolicy()
        # multi-rank: verdict exchange + epoch bookkeeping; a single-rank
        # transport needs no coordination
        self.coord = (
            EpochCoordinator(transport)
            if transport is not None and getattr(transport, "n_ranks", 1) > 1
            else None
        )
        if self.coord is not None:
            self.coord.epoch = getattr(dataset, "pass_epoch", 0)
        # elastic membership: a dead peer becomes a verdict round + owner-
        # ship shrink + shard adoption instead of a dead day. Requires the
        # coordinator (single-rank runs have no membership to lose) and a
        # dataset that carries an OwnershipMap.
        self.elastic = elastic
        if elastic is not None and self.coord is not None:
            self.coord.raise_peer_dead = True
        # set when ownership flipped mid-chain: the next checkpoint save
        # re-anchors with a base (a delta must not straddle an epoch flip)
        self._force_base = False
        # the map the LAST ownership flip replaced: adoption falls back to
        # it when a dead rank's chain predates the flip (it died before
        # its own re-anchor save committed)
        self._prev_ownership = None
        self.round_to = round_to
        self.shrink = shrink
        self.on_give_up = on_give_up
        self._on_poisoned = on_poisoned
        # poisoned pass admitted under the degrade policy: the next
        # begin_pass (and any revert-retry of it) must bypass the gate
        self._admit_poisoned = False
        # default the dataset's dead-letter dir under the durable root so
        # quarantined records live next to the checkpoints they shadow
        if (
            checkpoint is not None
            and getattr(dataset, "quarantine_dir", "absent") is None
        ):
            dataset.quarantine_dir = os.path.join(checkpoint.root, "quarantine")
        # backend bring-up through the watchdog (no-op when jax is already
        # initialized — i.e. in every in-process test — but a cold trainer
        # entrypoint on a wedged TPU falls back to CPU instead of hanging),
        # then the persistent compile cache: "auto" resolves under the
        # durable checkpoint root, next to the checkpoints it warms
        from paddlebox_tpu.utils import backendguard, compilecache

        self.backend_verdict = backendguard.ensure_backend()
        cache_dir = compilecache.resolve_dir(
            str(config.get_flag("compile_cache_dir")),
            ckpt_root=checkpoint.root if checkpoint is not None else None,
        )
        if cache_dir is not None:
            compilecache.enable(cache_dir)
        # telemetry plane: metric series + incident bundles live under the
        # durable checkpoint root (obs/) so postmortems travel with the
        # artifacts they explain; without a checkpoint both stay off
        # unless the obs_incident_dir flag points somewhere explicitly
        self.metrics: Optional[MetricsWriter] = None
        self._incident_dir: Optional[str] = None
        if checkpoint is not None:
            obs_dir = os.path.join(checkpoint.root, "obs")
            rank = getattr(transport, "rank", 0) if transport is not None else 0
            self.metrics = MetricsWriter(obs_dir, rank=rank)
            self._incident_dir = os.path.join(obs_dir, "incidents")
        self.incidents: List[Incident] = []
        self._auc_history: deque = deque(maxlen=self.gates.auc_window)
        self._pass_seq = 0
        self._date: Optional[str] = None
        # (date, tuple(files)) of the pass whose load this supervisor kicked
        # into the dataset's boundary feed stage. The marker doubles as the
        # "set_date already consumed" record: a kicked pass's set_date runs
        # at kick time, so the adopting (or falling-back) run_pass must NOT
        # call it again — pass_id would double-bump and shift the load's
        # sampling/shuffle seeds off the sequential run's.
        self._prefetch: Optional[tuple] = None

    # ---- incident log ----------------------------------------------------

    def _record(self, kind: str, action: str, attempt: int, detail: str = "") -> Incident:
        inc = Incident(
            pass_seq=self._pass_seq,
            date=self._date,
            kind=kind,
            action=action,
            attempt=attempt,
            detail=detail,
        )
        self.incidents.append(inc)
        STAT_ADD("supervisor_incidents")
        # one literal per kind (MON005): the incident vocabulary is closed
        # (Incident.kind docstring), so the metric family stays enumerable
        if kind == "load_error":
            STAT_ADD("supervisor_load_error")
        elif kind == "prefetch_error":
            STAT_ADD("supervisor_prefetch_error")
        elif kind == "data_poisoned":
            STAT_ADD("supervisor_data_poisoned")
        elif kind == "ckpt_save_error":
            STAT_ADD("supervisor_ckpt_save_error")
        elif kind == "peer_abort":
            STAT_ADD("supervisor_peer_abort")
        elif kind == "train_error":
            STAT_ADD("supervisor_train_error")
        elif kind == "escalate_resume":
            STAT_ADD("supervisor_escalate_resume")
        elif kind == "gave_up":
            STAT_ADD("supervisor_gave_up")
        elif kind == "gate_nan":
            STAT_ADD("supervisor_gate_nan")
        elif kind == "gate_auc":
            STAT_ADD("supervisor_gate_auc")
        elif kind == "rank_death":
            STAT_ADD("supervisor_rank_death")
        elif kind == "migrate":
            STAT_ADD("supervisor_migrate")
        elif kind == "migrate_abort":
            STAT_ADD("supervisor_migrate_abort")
        else:  # pragma: no cover - new kinds must be added above
            STAT_ADD("supervisor_other")
        PROFILER.instant(f"supervisor:{kind}", inc.as_dict())
        if kind in _FATAL_INCIDENT_KINDS and action != "degrade":
            # the pass is lost: publish the last N spans + stat snapshot
            # + this incident as an atomic incident-<ts>.json bundle
            FLIGHT_RECORDER.dump(
                f"supervisor_{kind}", detail, dir_path=self._incident_dir
            )
        return inc

    # ---- pieces ----------------------------------------------------------

    def _load_with_retry(self, date: Optional[str], files: Sequence[str]) -> None:
        for attempt in range(self.retry.retries + 1):
            try:
                if date is not None:
                    self.ds.set_date(date)
                self.ds.set_filelist(list(files))
                self.ds.load_into_memory()
                return
            except Exception as e:
                # the fs tier already burned its own retry-until-open
                # budget; reaching here means the input is still missing
                # or the reader died mid-stream
                if attempt >= self.retry.retries:
                    self._record("load_error", "raise", attempt, repr(e))
                    raise PassFailure(
                        f"load failed after {attempt + 1} attempts: {e}"
                    ) from e
                self._record("load_error", "retry", attempt, repr(e))
                self.retry.sleep(self.retry.backoff(attempt + 1))

    def _kick_prefetch(self, date: Optional[str], files: Sequence[str]) -> None:
        """Stage the NEXT pass's load behind the live pass's training.

        Kicks the dataset's boundary feed pipeline — threaded read, key
        premerge, gated host-row prefetch (see BoxPSDataset.
        _stage_boundary_prefetch) — on the preload thread, so by the time
        ``run_pass`` reaches the next pass its data is already staged.
        Opportunistic: any failure here is an incident, never an attempt
        failure — the next ``run_pass`` falls back to a synchronous load.
        Coordinated (multi-rank) runs don't kick: the load there is itself
        a lockstep verdict exchange that must stay on the pass boundary.
        """
        if self.coord is not None or not config.get_flag("boundary_pipeline"):
            return
        key = (date, tuple(files))
        try:
            if date is not None and self._prefetch != key:
                self.ds.set_date(date)
            # marker set as soon as set_date is consumed: even if the kick
            # dies right after, the fallback load must skip set_date
            self._prefetch = key
            self.ds.set_filelist(list(files))
            self.ds.preload_into_memory()
        except Exception as e:
            self._record("prefetch_error", "deferred", 0, repr(e))

    def _adopt_prefetch(self, date: Optional[str], files: Sequence[str]) -> None:
        """Consume (or cancel) a previously kicked prefetch, then ensure the
        pass's data is staged — falling back to the synchronous retrying
        load when the kick failed, was reverted away, or targeted a
        different pass."""
        marker, self._prefetch = self._prefetch, None
        key = (date, tuple(files))
        if marker == key:
            staged = False
            try:
                self.ds.wait_preload_done()
                # a revert (or a failed kick) may have discarded the staged
                # slot after the marker was set — verify before trusting it
                staged = self.ds._staged is not None
            except Exception as e:
                self._record("prefetch_error", "retry", 0, repr(e))
                self.ds.discard_staged()
            if not staged:
                # set_date already consumed at kick time: date=None
                self._load_with_retry(None, files)
            return
        if marker is not None:
            # stale kick — the caller changed the schedule; cancel it
            try:
                self.ds.wait_preload_done()
            except Exception:
                # the staged load is discarded either way, but a failed
                # one is still a failed load: count it, don't erase it
                STAT_ADD("supervisor_stale_preload_errors")
            self.ds.discard_staged()
        self._load_with_retry(date, files)

    @property
    def on_poisoned(self) -> str:
        """Effective poisoned-pass policy (constructor arg wins, else the
        on_poisoned_pass flag)."""
        v = self._on_poisoned or str(config.get_flag("on_poisoned_pass"))
        if v not in ("fail", "skip_pass", "degrade"):
            raise ValueError(
                f"on_poisoned_pass must be fail|skip_pass|degrade, got {v!r}"
            )
        return v

    def _poison_report(self) -> Optional[Dict[str, Any]]:
        """The dataset's admission verdict for the loaded pass (None for
        datasets without the quarantine surface, e.g. test doubles)."""
        rep_fn = getattr(self.ds, "admission_report", None)
        return rep_fn() if rep_fn is not None else None

    def _handle_poisoned(
        self, detail: str, rep: Optional[Dict[str, Any]]
    ) -> bool:
        """Apply the on_poisoned policy to an already-global poison verdict.
        True -> proceed with the pass (degrade), False -> drop it
        (skip_pass); the fail policy raises DataPoisonedError."""
        policy = self.on_poisoned
        loss = ""
        if rep is not None and (rep["bad_lines"] or rep["bad_files"]):
            loss = (
                f" (loss: {rep['bad_lines']} lines / {rep['bad_files']} "
                f"files, line_fraction={rep['line_fraction']:.5f})"
            )
        if policy == "degrade":
            self._record("data_poisoned", "degrade", 0, detail + loss)
            self._admit_poisoned = True
            return True
        if policy == "skip_pass":
            self._record("data_poisoned", "skip", 0, detail + loss)
            drop = getattr(self.ds, "drop_pass_data", None)
            if drop is not None:
                drop()
            return False
        self._record("data_poisoned", "raise", 0, detail + loss)
        raise DataPoisonedError(
            detail, report=rep, dead_letter=(rep or {}).get("dead_letter")
        )

    def _gate(self, out: Dict[str, float]) -> None:
        g = self.gates
        batches = out.get("batches", 0.0)
        if batches:
            ratio = out.get("nan_batches", 0.0) / batches
            if ratio > g.nan_ratio_max:
                raise PassRejected(
                    "nan",
                    f"{ratio:.3f} of batches NaN-skipped "
                    f"(max {g.nan_ratio_max:.3f})",
                )
        auc = out.get("auc")
        if auc is None or not np.isfinite(auc):
            return
        if g.auc_absolute_floor is not None and auc < g.auc_absolute_floor:
            raise PassRejected(
                "auc", f"auc {auc:.4f} under absolute floor {g.auc_absolute_floor:.4f}"
            )
        if len(self._auc_history) >= g.auc_min_history:
            floor = float(np.mean(self._auc_history)) - g.auc_floor_margin
            if auc < floor:
                raise PassRejected(
                    "auc",
                    f"auc {auc:.4f} under trailing floor {floor:.4f} "
                    f"(window of {len(self._auc_history)} confirmed passes)",
                )

    def _attempt(
        self, n_batches: Optional[int], prefetch: Optional[tuple] = None
    ) -> Dict[str, float]:
        """One armed begin->train->gate->[global verdict]->confirm cycle."""
        err: Optional[Exception] = None
        out: Dict[str, float] = {}
        try:
            if not self.ds._in_pass:
                # first attempt, or a revert re-armed the in-memory data.
                # admit_poisoned only reaches datasets that know the kwarg
                # (and only under the degrade policy) — test doubles and
                # older datasets keep their plain signature
                kw = {"admit_poisoned": True} if self._admit_poisoned else {}
                self.ds.begin_pass(
                    round_to=self.round_to, enable_revert=True, trainer=self.tr,
                    **kw,
                )
            self.tr.prepare_pass(self.ds, n_batches)
            if prefetch is not None:
                # training is about to occupy the device: stage the next
                # pass's load/premerge/prefetch behind it
                self._kick_prefetch(prefetch[0], prefetch[1])
            out = self.tr.train_pass(self.ds, n_batches=n_batches)
            # the trained table just landed: kick the host writeback now so
            # it overlaps the gate/verdict window instead of blocking the
            # boundary. Safe pre-verdict — the armed guard's revert covers
            # partial writeback, and revert_pass cancels the kick.
            if hasattr(self.ds, "kick_writeback"):
                self.ds.kick_writeback(self.tr.trained_table())
            self._gate(out)
        except Exception as e:
            if self.coord is None:
                raise
            # hold the local failure until the verdict is published: peers
            # are (or soon will be) waiting on this rank's vote, and only
            # a NO that every rank hears aborts the pass everywhere
            err = e
        if self.coord is not None:
            ok, detail = self.coord.exchange_verdict(
                f"pass:{self._pass_seq}", err is None, repr(err) if err else ""
            )
            if err is not None:
                raise err
            if not ok:
                raise CoordinatedAbort(detail)
        # confirm ONLY after the global verdict: the guard is still armed
        # up to here, so every rank that must revert still can
        # classic (host) writeback: a guard is armed, so the carried-table
        # boundary is gated off anyway — hand over the host copy
        self.ds.end_pass(self.tr.trained_table(), shrink=self.shrink)
        return out

    def _revert(self, attempt: int, cause: BaseException) -> None:
        if isinstance(cause, PassRejected):
            kind = f"gate_{cause.gate}"
        elif isinstance(cause, CoordinatedAbort):
            kind = "peer_abort"
        else:
            kind = "train_error"
        try:
            self.ds.revert_pass()
        except Exception as e:
            # an unrevertable pass (guard lost, revert itself died) can
            # only be healed by the durable tier
            self._record(kind, "revert_failed", attempt, f"{cause!r}; revert: {e!r}")
            raise PassFailure(f"revert failed after {cause!r}: {e}") from e
        self._record(kind, "revert_retry", attempt, repr(cause))

    def _escalate(self, attempt: int, cause: BaseException) -> None:
        """Resume the last durable (manifest-verified) state and re-enter."""
        state = self.checkpoint.resume(self.table, self.tr)
        # external overwrite of table rows + dense params: the trainer's
        # cached device state is stale now
        self.tr._state = None
        self.tr._state_ws = None
        self._record(
            "escalate_resume", "resume", attempt, f"{cause!r} -> resumed {state}"
        )

    def _save_checkpoint(self, mode: str) -> None:
        assert self.checkpoint is not None
        for attempt in range(self.retry.retries + 1):
            try:
                if mode == "base" or self._force_base:
                    # an ownership flip mid-day re-anchors the chain: the
                    # old chain's deltas cover the pre-flip key ranges and
                    # must not be extended across the epoch
                    self.checkpoint.save_base(self._date, self.table, self.tr)
                    self._force_base = False
                else:
                    self.checkpoint.save_delta(self._date, self.table, self.tr)
                return
            except MembershipEpochError as e:
                # belt-and-braces: the cursor says the chain predates this
                # rank's ownership epoch — re-anchor instead of retrying
                # the refused delta
                self._record("ckpt_save_error", "retry", attempt, repr(e))
                self._force_base = True
            except Exception as e:
                # atomic publishing means a failed attempt left nothing
                # under a final name — a retry starts clean
                if attempt >= self.retry.retries:
                    self._record("ckpt_save_error", "raise", attempt, repr(e))
                    raise PassFailure(
                        f"checkpoint {mode} save failed after "
                        f"{attempt + 1} attempts: {e}"
                    ) from e
                self._record("ckpt_save_error", "retry", attempt, repr(e))
                self.retry.sleep(self.retry.backoff(attempt + 1))
        raise PassFailure(
            f"checkpoint {mode} save failed: retry budget exhausted "
            "re-anchoring across an ownership-epoch flip"
        )

    # ---- elastic membership ---------------------------------------------

    def _ownership_map(self):
        """The dataset's current OwnershipMap, defaulting to the even
        split over all transport ranks (epoch 0) when none was installed
        yet — identical to what DistributedWorkingSet defaults to."""
        omap = getattr(self.ds, "ownership", None)
        if omap is None:
            omap = _membership.OwnershipMap.even(
                self.ds.n_mesh_shards, self.coord.transport.n_ranks
            )
        return omap

    def _install_ownership(self, new_map, prev_map=None) -> None:
        """Atomically adopt a successor OwnershipMap: dataset routing,
        checkpoint epoch, and the chain re-anchor flip together.

        The re-anchor base save happens HERE, before any training resumes
        under the new map — not at the next pass boundary. Deferring it
        opens a window where a rank that dies mid-pass leaves a chain
        predating the flip: shard ranges it gained in the flip would be
        absent from (or stale in) that chain, and adoption would silently
        restore them from the seeded init. A rank whose re-anchor save
        itself fails raises (PassFailure after retries) and is shrunk out
        by the survivors, whose adoption then uses the previous owners'
        chains for its un-anchored gained ranges (``_prev_ownership``).

        ``prev_map`` overrides what is recorded as the map this flip
        replaced — the membership round passes its SYNCED base so every
        survivor records the same predecessor, even one that re-entered
        the round a map behind its peers."""
        self._prev_ownership = (
            prev_map if prev_map is not None else self._ownership_map()
        )
        self.ds.ownership = new_map
        if self.checkpoint is not None:
            self.checkpoint.ownership_epoch = new_map.epoch
        self._force_base = True
        STAT_SET("membership.epoch", new_map.epoch)
        if self.checkpoint is not None and self._date is not None:
            self._save_checkpoint("base")

    def _handle_rank_death(self, e: PeerDeadError) -> None:
        """Survivor-side membership change: verdict round -> map sync ->
        shrunk map -> shard adoption from the dead ranks' durable
        checkpoint shards.

        Re-entrant under further deaths: a peer dying WHILE the round runs
        surfaces as a nested PeerDeadError from any of its collectives;
        rather than killing the day, the new evidence is unioned into the
        dead set and the whole round re-runs from the refreshed set —
        bounded by the rank count, since each re-entry strictly grows it.

        On return the retried pass runs on the survivors over exactly the
        table state a fresh shrunk-membership run would hold (adoption is
        an idempotent upsert from the last pass boundary, and keys never
        checkpointed are recreated from the seeded init — both bitwise-
        equal to the fresh run, pinned by tests/test_elastic.py)."""
        assert self.elastic is not None and self.coord is not None
        tp = self.coord.transport
        last = e
        for round_no in range(tp.n_ranks + 1):
            tp.mark_dead(last.dead)
            try:
                self._membership_round(last)
                return
            except PeerDeadError as nested:
                last = nested
                self._record(
                    "rank_death", "retry", round_no,
                    f"peer died mid-membership-round: {nested!r}",
                )
        raise PassFailure(
            f"membership change did not converge within {tp.n_ranks + 1} "
            f"rounds; last evidence: {last!r}"
        ) from last

    def _membership_round(self, e: PeerDeadError) -> None:
        """One attempt of the membership change; raises PeerDeadError when
        yet another peer dies mid-round (caller unions and re-enters)."""
        tp = self.coord.transport
        # revert anything the dying attempt armed before touching the table
        if getattr(self.ds, "_in_pass", False):
            try:
                self.ds.revert_pass()
            except Exception as re_err:
                self._record(
                    "rank_death", "revert_failed", 0,
                    f"{e!r}; revert: {re_err!r}",
                )
                raise PassFailure(
                    f"revert failed after peer death {e!r}: {re_err}"
                ) from re_err
        self.coord.advance(getattr(self.ds, "pass_epoch", None))
        # membership verdict round: every survivor converges on one dead
        # set (the proposal is encoded in the collective tag)
        agreed = _membership.agree_membership(
            tp, self._pass_seq, timeout=self.elastic.member_timeout
        )
        # map sync: a survivor whose PREVIOUS round was cut short by this
        # death re-enters one map behind its peers; all derive the
        # successor from the highest-epoch base so epochs and boundaries
        # agree everywhere (divergent same-epoch maps raise — split-brain)
        old_map = self._ownership_map()
        base_map = _membership.sync_map(
            tp, self._pass_seq, agreed, old_map,
            timeout=self.elastic.member_timeout,
        )
        # adoption sources are judged against MY installed map: a rank
        # that missed an intermediate flip never adopted its pieces, so
        # for it each dead rank's range is the wider pre-flip one
        newly_dead = [d for d in agreed if old_map.is_live(d)]
        new_map = base_map.shrink(agreed)
        my_rank = tp.rank
        adopted_ranges = []
        for d in newly_dead:
            dlo, dhi = old_map.range_of(d)
            mlo, mhi = new_map.range_of(my_rank)
            lo, hi = max(dlo, mlo), min(dhi, mhi)
            if lo < hi:
                adopted_ranges.append([lo, hi])
        # adoption: bounded retries in ISOLATION — the pass must not retry
        # under a half-installed map (keys routed to a dead owner would
        # silently vanish from the exchange)
        adopt_err: Optional[Exception] = None
        adopted_keys = 0
        for a in range(self.elastic.adopt_retries + 1):
            try:
                adopted_keys = sum(
                    _membership.adopt_dead_shards(
                        self.table, self.elastic.shared_root, d,
                        old_map, new_map, my_rank,
                        prev_map=self._prev_ownership,
                    )
                    for d in newly_dead
                )
                adopt_err = None
                break
            except Exception as ae:
                adopt_err = ae
                if a < self.elastic.adopt_retries:
                    self._record("rank_death", "retry", a, repr(ae))
                    self.retry.sleep(self.retry.backoff(a + 1))
        # every survivor must finish adopting before anyone re-enters the
        # pass — and one survivor failing adoption aborts all (the dead
        # ranges would be served by nobody). The tag carries the successor
        # map's epoch AND content fingerprint: post-sync these are
        # identical everywhere, so a mismatch can only mean a protocol
        # bug — it stalls loudly instead of committing divergent maps.
        ok, detail = self.coord.exchange_verdict(
            f"member:{self._pass_seq}:{new_map.epoch}:{new_map.fingerprint()}",
            adopt_err is None,
            repr(adopt_err) if adopt_err else "",
        )
        if adopt_err is not None:
            self._record("rank_death", "raise", 0, repr(adopt_err))
            raise PassFailure(
                f"shard adoption failed after {self.elastic.adopt_retries + 1} "
                f"attempts: {adopt_err}"
            ) from adopt_err
        if not ok:
            self._record("rank_death", "raise", 0, detail)
            raise PassFailure(f"peer shard adoption failed: {detail}")
        self._install_ownership(new_map, prev_map=base_map)
        self._record(
            "rank_death", "revert_retry", 0,
            f"dead={list(agreed)} survivors={list(new_map.live_ranks)} "
            f"ownership_epoch={new_map.epoch} adopted_keys={adopted_keys}",
        )
        bundle = {
            "dead": [int(d) for d in agreed],
            "survivors": [int(r) for r in new_map.live_ranks],
            "ownership_epoch": new_map.epoch,
            "adopted_ranges": adopted_ranges,
            "adopted_keys": int(adopted_keys),
        }
        FLIGHT_RECORDER.note_incident("membership_change", bundle)
        FLIGHT_RECORDER.dump(
            "rank_death", json.dumps(bundle), dir_path=self._incident_dir
        )
        PROFILER.instant("supervisor:membership_change", bundle)

    def _maybe_migrate(self) -> None:
        """Planned migration at a confirmed pass boundary: recut ownership
        boundaries when per-rank key-load skew crosses the threshold and
        stream the moving shard ranges owner->owner. Atomic at the
        boundary: receivers stage, a commit verdict decides, and only a
        global YES flips the epoch — any failure leaves the old epoch
        serving and the plan is re-derived at the next boundary."""
        from paddlebox_tpu.table.sparse_table import key_to_shard

        assert self.elastic is not None and self.coord is not None
        tp = self.coord.transport
        omap = self._ownership_map()
        if len(omap.live_ranks) < 2:
            return
        # the carried device table may hold rows the host store lags on —
        # migration reads host rows, so everything owed must land first
        drain = getattr(self.table, "drain_pending", None)
        if drain is not None:
            drain()
        lo, hi = omap.range_of(tp.rank)
        keys = self.table.keys()
        sh = key_to_shard(keys, omap.n_mesh_shards)
        mine = sh[(sh >= lo) & (sh < hi)]
        local = np.bincount(mine - lo, minlength=hi - lo).astype("<i8")
        views = tp.allgather(
            local.tobytes(),
            f"ctl:load:{self._pass_seq}@e{self.coord.epoch}",
            timeout=self.elastic.member_timeout,
        )
        loads = np.zeros(omap.n_mesh_shards, np.int64)
        for r in omap.live_ranks:
            rlo, rhi = omap.range_of(r)
            v = views[r]
            if len(v) != (rhi - rlo) * 8:
                # never recut from a silently zero-filled view: the plan
                # would be deterministic (all ranks see the same garbage)
                # yet systematically wrong
                STAT_ADD("membership.load_view_errors")
                raise RuntimeError(
                    f"load view from rank {r} has {len(v)} bytes, expected "
                    f"{(rhi - rlo) * 8} for shard range [{rlo},{rhi})"
                )
            loads[rlo:rhi] = np.frombuffer(v, dtype="<i8")
        new_map = _membership.plan_rebalance(
            omap, loads, self.elastic.migrate_skew
        )
        if new_map is None:
            # every rank derived None from the identical global vector —
            # no verdict round needed for a unanimous no-op
            return
        seq = f"{self._pass_seq}.{new_map.epoch}"
        xfer = None
        xfer_err: Optional[Exception] = None
        try:
            xfer = _membership.migrate_ranges(
                tp, self.table, omap, new_map, seq, self.coord.epoch,
                timeout=self.elastic.member_timeout,
            )
        except Exception as me:
            xfer_err = me
        # the commit verdict must be ATOMIC: a rank whose verdict round
        # merely times out cannot tell whether peers committed, so folding
        # the timeout into a local "no" would leave it on the old map while
        # peers flip — colliding epoch numbers over divergent boundaries.
        # fatal=True makes local transport failure here raise instead; this
        # rank dies with PassFailure and the survivors shrink it out. The
        # tag carries the successor map's content fingerprint so bases that
        # diverged for any other reason stall loudly rather than commit.
        try:
            ok, detail = self.coord.exchange_verdict(
                f"migrate:{seq}:{new_map.fingerprint()}",
                xfer_err is None,
                repr(xfer_err) if xfer_err else "",
                fatal=True,
            )
        except PeerDeadError:
            raise  # a DEAD peer is decidable — membership handling owns it
        except (OSError, TimeoutError) as ve:
            STAT_ADD("membership.migrations_aborted")
            self._record("migrate_abort", "raise", 0, repr(ve))
            raise PassFailure(
                f"migrate commit verdict uncertain (transport failure "
                f"mid-round): {ve!r}"
            ) from ve
        if not ok or xfer_err is not None:
            # old epoch still serves; staged pieces are discarded and the
            # plan is re-derived at the next boundary (FLT008 contract)
            STAT_ADD("membership.migrations_aborted")
            self._record(
                "migrate_abort", "retry", 0,
                detail or repr(xfer_err),
            )
            return
        _membership.commit_staged(self.table, xfer["staged"])
        self._install_ownership(new_map)
        STAT_ADD("membership.migrated_keys", int(xfer["recv_keys"]))
        STAT_ADD("membership.migration_bytes", int(xfer["sent_bytes"]))
        self._record(
            "migrate", "commit", 0,
            f"ownership_epoch={new_map.epoch} moves={xfer['moves']} "
            f"recv_keys={xfer['recv_keys']} sent_bytes={xfer['sent_bytes']}",
        )
        FLIGHT_RECORDER.note_incident(
            "migration", {
                "ownership_epoch": new_map.epoch,
                "moves": xfer["moves"],
                "recv_keys": int(xfer["recv_keys"]),
                "sent_bytes": int(xfer["sent_bytes"]),
            },
        )

    # ---- the supervised pass --------------------------------------------

    def run_pass(
        self,
        files: Sequence[str],
        date: Optional[str] = None,
        n_batches: Optional[int] = None,
        save: Optional[str] = None,  # None | "base" | "delta"
        prefetch: Optional[tuple] = None,  # (date, files) of the NEXT pass
    ) -> Optional[Dict[str, float]]:
        """Load, train, gate, and publish one pass, healing failures.

        ``prefetch`` names the pass that follows this one: once training is
        underway its load is kicked into the dataset's boundary feed stage,
        and the next ``run_pass`` over the same (date, files) adopts the
        staged result instead of loading synchronously (``run_day`` threads
        this automatically).

        Returns the pass metrics, or None when the pass was dropped
        (``on_give_up="skip"`` after retries AND escalation failed).
        """
        if save not in (None, "base", "delta"):
            raise ValueError(f"save must be None, 'base' or 'delta', got {save!r}")
        if save is not None and self.checkpoint is None:
            raise ValueError("save requires a CheckpointManager")
        self._pass_seq += 1
        self._date = date if date is not None else self._date
        self._admit_poisoned = False
        pass_t0 = time.monotonic()
        if self.coord is None:
            self._adopt_prefetch(date, files)
        else:
            # coordinate the load the same way as the pass verdict: a rank
            # whose input never materialized must take every peer down with
            # it NOW, not leave them hanging in the first exchange
            while True:
                load_err: Optional[PassFailure] = None
                try:
                    self._load_with_retry(date, files)
                except PassFailure as e:
                    load_err = e
                try:
                    ok, detail = self.coord.exchange_verdict(
                        f"load:{self._pass_seq}",
                        load_err is None,
                        repr(load_err) if load_err else "",
                    )
                except PeerDeadError as e:
                    # only raised in elastic mode: shrink membership and
                    # redo the (unarmed) load on the survivors
                    if self.elastic is None:
                        raise
                    self._handle_rank_death(e)
                    continue
                break
            if load_err is not None:
                raise load_err
            if not ok:
                # nothing armed yet — no revert, just a clean global stop
                self._record("peer_abort", "raise", 0, detail)
                raise PassFailure(
                    f"pass {self._pass_seq} aborted: peer load failed: {detail}"
                )
        # poison-aware admission: DataPoisonedError is DETERMINISTIC — the
        # same filelist replays the same corruption on every attempt, so it
        # is resolved here, before the retry loop, under the on_poisoned
        # policy. In coordinated runs the verdict rides the same allgather
        # as the pass/load verdicts so every rank admits or rejects in
        # lockstep (one rank degrading a pass its peer re-runs clean would
        # desync the working-set exchange).
        rep = self._poison_report()
        poisoned = rep is not None and rep["poisoned"]
        poison_detail = rep["detail"] if poisoned else ""
        if self.coord is not None and rep is not None:
            ok, gdetail = self.coord.exchange_verdict(
                f"poison:{self._pass_seq}", not poisoned, poison_detail
            )
            if not ok and not poisoned:
                poisoned = True
                poison_detail = f"peer pass data poisoned: {gdetail}"
        if poisoned and not self._handle_poisoned(poison_detail, rep):
            return None
        escalated = False
        attempt = 0
        while True:
            try:
                with PROFILER.record_event("supervised_pass_attempt", "supervisor"):
                    out = self._attempt(n_batches, prefetch=prefetch)
                break
            except DataPoisonedError as e:
                # belt-and-braces: the pre-loop check above resolves poison
                # before anything is armed, so reaching here means the
                # thresholds/policy changed under a live attempt. Still
                # deterministic — never burn backoff retries on it.
                self._record("data_poisoned", "raise", attempt, repr(e))
                raise
            except PeerDeadError as e:
                if self.elastic is None or self.coord is None:
                    # hardware loss without elastic membership stays what
                    # it always was: terminal for the day
                    raise
                # membership event, not a pass failure: verdict round,
                # ownership shrink, adoption — then retry the pass on the
                # survivors with a FRESH budget (the hardware loss costs
                # one pass retry, never the day)
                self._handle_rank_death(e)
                attempt = 0
                escalated = False
                continue
            except Exception as e:
                self._revert(attempt, e)
                if self.coord is not None:
                    # revert_pass bumped ds.pass_epoch; adopt it (or bump
                    # our own for datasets without the counter) and purge
                    # the aborted attempt's in-flight frames
                    self.coord.advance(getattr(self.ds, "pass_epoch", None))
                attempt += 1
                if attempt > self.retry.retries:
                    if not escalated and self.checkpoint is not None:
                        self._escalate(attempt, e)
                        escalated = True
                        attempt = 0
                        continue
                    if self.on_give_up == "skip":
                        self._record("gave_up", "skip", attempt, repr(e))
                        return None
                    self._record("gave_up", "raise", attempt, repr(e))
                    raise PassFailure(
                        f"pass {self._pass_seq} failed after retries"
                        + (" and checkpoint resume" if escalated else "")
                    ) from e
                self.retry.sleep(self.retry.backoff(attempt))
        if self._admit_poisoned and rep is not None:
            # degrade accounting: the pass manifest records what was lost
            out["quarantined_line_fraction"] = float(rep["line_fraction"])
            out["quarantined_bad_lines"] = float(rep["bad_lines"])
            out["quarantined_bad_files"] = float(rep["bad_files"])
        auc = out.get("auc")
        if auc is not None and np.isfinite(auc):
            self._auc_history.append(float(auc))
        if save is not None:
            self._save_checkpoint(save)
        STAT_OBSERVE("supervisor.pass_s", time.monotonic() - pass_t0)
        if self.metrics is not None:
            # pass-boundary series point: counters + per-pass deltas +
            # histogram summaries, labeled so obs_report can build the
            # per-pass table without guessing at boundaries
            self.metrics.snapshot(
                f"pass:{self._pass_seq}",
                extra={
                    k: float(v)
                    for k, v in out.items()
                    if isinstance(v, (int, float)) and np.isfinite(v)
                },
            )
        return out

    def run_day(
        self,
        date: str,
        pass_files: Sequence[Sequence[str]],
        n_batches: Optional[int] = None,
        publish: bool = True,
    ) -> List[Optional[Dict[str, float]]]:
        """One day = base save after the first pass, delta saves after the
        rest (the reference's SaveBase + per-pass need_save_delta cadence).
        ``publish=False`` trains without checkpointing."""
        outs: List[Optional[Dict[str, float]]] = []
        do_save = publish and self.checkpoint is not None
        for p, files in enumerate(pass_files):
            mode = None if not do_save else ("base" if p == 0 else "delta")
            nxt = (
                (date, tuple(pass_files[p + 1]))
                if p + 1 < len(pass_files)
                else None
            )
            outs.append(
                self.run_pass(
                    files, date=date, n_batches=n_batches, save=mode,
                    prefetch=nxt,
                )
            )
            if (
                self.elastic is not None
                and self.coord is not None
                and self.elastic.migrate_skew > 1.0
            ):
                # confirmed + published boundary: the one place ownership
                # may move planned ranges (atomic epoch flip on a global
                # commit verdict)
                try:
                    self._maybe_migrate()
                except PeerDeadError as e:
                    # a rank died during the boundary round: membership
                    # handling, then the next pass runs on the survivors
                    self._handle_rank_death(e)
            if self.metrics is not None:
                # wall-clock cadence between the per-pass points: on long
                # passes obs_metrics_interval_s paces extra ticks
                self.metrics.maybe_snapshot()
        return outs
