"""PassSupervisor: the self-healing pass/day loop.

The repo has had the recovery *pieces* for a while — PassGuard
confirm/revert (train/rollback.py, FleetWrapper::Confirm/Revert parity),
retry-until-open on flaky inputs (utils/fs.py, data_feed.cc:2738-2740
parity), NaN-batch containment in the device step, and day-level
base+delta resume (train/checkpoint.py). What production actually needs is
the layer that COMPOSES them: a multi-day CTR run survives a bad pass
because something notices, reverts, retries, and — when retries don't help
— falls back to the last durable state and re-enters. That layer is
``PassSupervisor``.

One supervised pass runs:

    load (fs retries inside) -> begin_pass(enable_revert) [guard armed]
      -> prepare_pass -> train_pass -> health gates -> end_pass [confirm]
      -> optional checkpoint publish (base/delta, manifest-verified)

Any exception or gate rejection reverts the pass (bit-exact: retraining
after revert equals a never-interrupted run, pinned by
tests/test_rollback.py) and retries under bounded exponential backoff.
When ``max_retries`` is exhausted the supervisor escalates once: it
restores the last durable checkpoint state via ``CheckpointManager.
resume()`` (manifest-verified, torn-snapshot fallback) and re-enters with
a fresh retry budget. Every action lands in a structured incident log —
``self.incidents``, process-wide counters in utils/monitor, and instant
events in the utils/trace timeline.

Health gates (the "pass is poisoned" detectors the reference applies by
operator convention):

- NaN gate: the ratio of NaN-skipped batches (the step's containment
  counter) must stay under ``nan_ratio_max`` — a pass that skims over too
  many poisoned batches is itself poisoned.
- AUC floor: the pass AUC must not fall more than ``auc_floor_margin``
  below the trailing mean of the last ``auc_window`` CONFIRMED passes
  (only consulted after ``auc_min_history`` confirmations, so a cold
  start can't self-reject).

Poison awareness: corruption is NOT a transient fault. A load that
quarantined data beyond the admission thresholds (data/quarantine.py)
surfaces as ``DataPoisonedError`` — deterministic, because retrying the
same filelist replays the same corruption — so the supervisor resolves it
BEFORE the retry loop, without burning a single backoff retry, under the
``on_poisoned_pass`` policy: ``fail`` (raise, with a ``data_poisoned``
incident naming the dead-letter file), ``skip_pass`` (drop the pass's
data, keep the day), or ``degrade`` (train the pass with the quarantined
records dropped; the loss fraction lands in the incident and the pass
metrics). In coordinated runs the corrupt-fraction verdict rides the
same allgather as the pass/load verdicts, so every rank admits or
rejects in lockstep.

Distributed coordination (``transport=`` + :class:`EpochCoordinator`):
when the supervisor drives one rank of a multi-host run, a pass must
commit or revert GLOBALLY — one rank confirming a pass its peer reverted
leaves the host tables permanently diverged. So before ``end_pass`` every
rank publishes a verdict (my gates passed / my attempt raised) on a
control tag scoped by the current pass epoch; any NO — including a peer
that simply stopped answering, which times out the exchange — turns into
a :class:`CoordinatedAbort` on the healthy ranks, and every rank walks
the same revert path, bumps the same pass epoch (stale frames of the
aborted attempt are discarded by tag), and retries in lockstep. The
retried pass then runs over exactly the data + table state a clean run
would see, so its result is bitwise-equal to a never-faulted run
(tests/test_chaos_dist.py). Load failures coordinate the same way before
anything is armed. Escalation stays lockstep for free: verdicts are
global, every rank exhausts the same retry budget on the same attempt.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu import config
from paddlebox_tpu.data.quarantine import DataPoisonedError
from paddlebox_tpu.obs.flight_recorder import FLIGHT_RECORDER
from paddlebox_tpu.obs.metrics_writer import MetricsWriter
from paddlebox_tpu.parallel import membership as _membership
from paddlebox_tpu.parallel.transport import PeerDeadError
from paddlebox_tpu.train.checkpoint import MembershipEpochError, rank_root
from paddlebox_tpu.utils.faultinject import InjectedFault
from paddlebox_tpu.utils.faultinject import fire as _fault_fire
from paddlebox_tpu.utils.monitor import STAT_ADD, STAT_OBSERVE, STAT_SET
from paddlebox_tpu.utils.trace import PROFILER

# incident kinds that end a pass (or the day) rather than healing in
# place: each one flushes the flight recorder into an incident bundle
_FATAL_INCIDENT_KINDS = ("data_poisoned", "peer_abort", "gave_up")

# PBTX control tags of the elastic join protocol (grow half). The
# announce is an un-epoched knock — the joiner does not know the fleet's
# clocks yet, so the tag cannot carry them; the offer is addressed per
# joiner rank so a concurrent second announcer can never consume another
# rank's admission.
_JOIN_ANNOUNCE_TAG = "ctl:join:announce"
_JOIN_OFFER_TAG = "ctl:join:offer"

config.define_flag(
    "supervisor_max_retries",
    2,
    "revert+retry attempts per pass before the supervisor escalates to a "
    "checkpoint resume (and, failing that, gives up)",
)
config.define_flag(
    "on_poisoned_pass",
    "fail",
    "supervisor policy when a pass's load quarantined data beyond the "
    "admission thresholds (DataPoisonedError — deterministic, never "
    "retried): 'fail' raises, 'skip_pass' drops the pass and continues "
    "the day, 'degrade' trains over the pass with the quarantined "
    "records dropped (loss fraction recorded in the incident and the "
    "pass metrics)",
)


class PassRejected(RuntimeError):
    """A health gate rejected an otherwise-completed pass."""

    def __init__(self, gate: str, detail: str):
        super().__init__(f"pass rejected by {gate} gate: {detail}")
        self.gate = gate
        self.detail = detail


class PassFailure(RuntimeError):
    """The supervisor exhausted retries AND escalation for one pass."""


class CoordinatedAbort(RuntimeError):
    """A peer rank voted NO on this pass (its gate fired or its attempt
    raised), or the verdict exchange itself failed — this rank's locally
    healthy attempt must revert so the cluster retries in lockstep."""

    def __init__(self, detail: str):
        super().__init__(f"pass aborted by peer verdict: {detail}")
        self.detail = detail


class EpochCoordinator:
    """Control-plane verdict exchange + pass-epoch bookkeeping for one rank.

    ``exchange_verdict`` is an allgather on tag ``ctl:verdict:<key>@e<N>``
    (payload ``b"\\x01"`` = ok, ``b"\\x00" + detail`` = abort): it returns
    the GLOBAL verdict, and treats its own transport failure/timeout as an
    abort vote — a rank that cannot hear its peers must not confirm.
    ``advance`` bumps the epoch after a revert and raises the transport's
    stale-frame floor, so nothing a reverted attempt left in flight can
    reach the retried attempt's exchanges (the epoch suffix is the same
    ``@e<N>`` convention DistributedWorkingSet tags carry)."""

    def __init__(self, transport, timeout: Optional[float] = None):
        self.transport = transport
        self.timeout = timeout
        self.epoch = 0
        # elastic mode re-raises PeerDeadError instead of folding it into
        # an abort vote: a dead peer is a MEMBERSHIP event (verdict round,
        # ownership shrink, adoption), not a retryable pass failure — the
        # supervisor's death handler owns it. Off by default so
        # non-elastic runs keep the historical fail-as-abort behavior.
        self.raise_peer_dead = False

    def exchange_verdict(
        self, key: str, ok: bool, detail: str = "", fatal: bool = False
    ):
        """Returns (global_ok, detail) after every rank has voted.

        ``fatal=True`` re-raises a LOCAL transport failure/timeout instead
        of folding it into a NO vote. A commit-point exchange (the migrate
        epoch flip) must use it: a rank that times out cannot tell whether
        its peers committed, and quietly voting NO while they did leaves
        this rank serving the old map against their new one — split-brain
        the epoch integer can't detect. Better to die loudly and be shrunk
        out by the survivors."""
        payload = b"\x01" if ok else b"\x00" + detail.encode()[:512]
        tag = f"ctl:verdict:{key}@e{self.epoch}"
        try:
            votes = self.transport.allgather(payload, tag, timeout=self.timeout)
        except PeerDeadError as e:
            if self.raise_peer_dead:
                raise
            STAT_ADD("supervisor_verdict_exchange_errors")
            return False, f"verdict exchange failed: {e!r}"
        except (OSError, TimeoutError) as e:
            STAT_ADD("supervisor_verdict_exchange_errors")
            if fatal:
                raise
            return False, f"verdict exchange failed: {e!r}"
        # membership-confirmed dead ranks contribute b"" placeholder slots,
        # not NO votes
        live_fn = getattr(self.transport, "live_ranks", None)
        live = set(live_fn()) if live_fn is not None else set(
            range(self.transport.n_ranks)
        )
        bad = [
            f"rank {r}: {v[1:].decode(errors='replace') or 'aborted'}"
            for r, v in enumerate(votes)
            if r in live and v[:1] != b"\x01"
        ]
        if bad:
            return False, "; ".join(bad)
        return True, ""

    def advance(self, epoch: Optional[int] = None) -> None:
        """Enter the next pass epoch (or adopt the dataset's counter, which
        revert_pass bumps — keeping the two in lockstep)."""
        self.epoch = self.epoch + 1 if epoch is None else epoch
        self.transport.discard_epochs_below(self.epoch)


@dataclass
class ElasticConfig:
    """Opt-in elastic membership for a coordinated supervisor.

    ``shared_root`` is the day root every rank publishes its checkpoint
    tree under (``rank-<r>`` subdirs, checkpoint.rank_root): the adoption
    path opens a DEAD rank's tree read-only through it. ``migrate_skew``
    > 1.0 additionally arms planned migration: at a confirmed pass
    boundary, when the max/mean per-rank key-load ratio crosses it, the
    supervisor recuts ownership boundaries and streams the moving ranges
    (see docs/ROBUSTNESS.md, "Elastic membership & key migration").

    The grow half (docs/ROBUSTNESS.md, "Elastic grow & autoscale"):
    ``initial_live`` names the ranks actually RUNNING at day start when
    the transport's endpoint list reserves slots for future joiners —
    the supervisor marks the others dead and installs the even ownership
    split over the initial set. ``target_ranks`` is the autoscale
    ceiling: a waiting joiner is admitted at a published pass boundary
    only while the live count is below it (None admits whenever one
    knocks). ``hot_migrate`` switches the planned-migration load vector
    from raw key counts to the Parallax-style hotness prior (tier
    residency + decayed shows, table/dist_ws.hot_shard_loads) — the
    joiner carve is ALWAYS hotness-weighted."""

    shared_root: str
    migrate_skew: float = 0.0  # <= 1.0 disables planned migration
    adopt_retries: int = 2
    member_timeout: Optional[float] = None
    target_ranks: Optional[int] = None
    initial_live: Optional[Sequence[int]] = None
    hot_migrate: bool = False


@dataclass
class HealthGates:
    nan_ratio_max: float = 0.05
    auc_window: int = 5
    auc_min_history: int = 3
    auc_floor_margin: float = 0.05
    auc_absolute_floor: Optional[float] = None


@dataclass
class RetryPolicy:
    max_retries: Optional[int] = None  # None -> supervisor_max_retries flag
    backoff_s: float = 0.5
    backoff_mult: float = 2.0
    backoff_max_s: float = 30.0
    # injectable for tests (chaos schedules must not wall-clock sleep)
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    @property
    def retries(self) -> int:
        if self.max_retries is not None:
            return self.max_retries
        return int(config.get_flag("supervisor_max_retries"))

    def backoff(self, attempt: int) -> float:
        return min(
            self.backoff_s * self.backoff_mult ** max(0, attempt - 1),
            self.backoff_max_s,
        )


@dataclass
class Incident:
    """One structured entry of the supervisor's incident log."""

    pass_seq: int
    date: Optional[str]
    kind: str      # load_error | train_error | gate_nan | gate_auc |
                   # prefetch_error | ckpt_save_error | escalate_resume |
                   # gave_up | skipped | peer_abort | data_poisoned |
                   # rank_death | migrate | migrate_abort | rank_join |
                   # join_abort
    action: str    # retry | revert_retry | resume | raise | skip
    attempt: int
    detail: str = ""
    wall_time: float = field(default_factory=time.time)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "pass_seq": self.pass_seq,
            "date": self.date,
            "kind": self.kind,
            "action": self.action,
            "attempt": self.attempt,
            "detail": self.detail,
            "wall_time": self.wall_time,
        }


class PassSupervisor:
    """Fault-tolerant driver for the pass/day loop of one trainer.

    ``checkpoint`` (a CheckpointManager) enables both the escalation path
    and the per-pass publishing ``run_day`` performs; without it the
    supervisor still reverts/retries but gives up when retries exhaust.
    """

    def __init__(
        self,
        dataset,
        trainer,
        checkpoint=None,
        gates: Optional[HealthGates] = None,
        retry: Optional[RetryPolicy] = None,
        round_to: int = 512,
        shrink: bool = True,
        on_give_up: str = "raise",  # raise | skip (drop the pass, keep the day)
        transport=None,
        on_poisoned: Optional[str] = None,  # None -> on_poisoned_pass flag
        elastic: Optional[ElasticConfig] = None,
    ):
        if on_give_up not in ("raise", "skip"):
            raise ValueError(f"on_give_up must be 'raise' or 'skip', got {on_give_up!r}")
        if on_poisoned not in (None, "fail", "skip_pass", "degrade"):
            raise ValueError(
                "on_poisoned must be None, 'fail', 'skip_pass' or "
                f"'degrade', got {on_poisoned!r}"
            )
        self.ds = dataset
        self.tr = trainer
        self.table = dataset.table
        self.checkpoint = checkpoint
        self.gates = gates or HealthGates()
        self.retry = retry or RetryPolicy()
        # multi-rank: verdict exchange + epoch bookkeeping; a single-rank
        # transport needs no coordination
        self.coord = (
            EpochCoordinator(transport)
            if transport is not None and getattr(transport, "n_ranks", 1) > 1
            else None
        )
        if self.coord is not None:
            self.coord.epoch = getattr(dataset, "pass_epoch", 0)
        # elastic membership: a dead peer becomes a verdict round + owner-
        # ship shrink + shard adoption instead of a dead day. Requires the
        # coordinator (single-rank runs have no membership to lose) and a
        # dataset that carries an OwnershipMap.
        self.elastic = elastic
        if elastic is not None and self.coord is not None:
            self.coord.raise_peer_dead = True
            tp = self.coord.transport
            if elastic.initial_live is not None:
                # the endpoint list reserves slots for FUTURE joiners: only
                # initial_live ranks are running now. Mark the rest dead so
                # collectives don't wait on empty slots, and start from the
                # even ownership split over the actual fleet.
                live0 = sorted(int(r) for r in elastic.initial_live)
                if tp.rank not in live0:
                    raise ValueError(
                        f"rank {tp.rank} is not in initial_live {live0} — "
                        "a rank outside the initial fleet joins via "
                        "join_day, not run_day"
                    )
                tp.mark_dead([r for r in range(tp.n_ranks) if r not in live0])
                if getattr(dataset, "ownership", None) is None:
                    dataset.ownership = _membership.OwnershipMap.even_over(
                        dataset.n_mesh_shards, live0
                    )
            omap0 = getattr(dataset, "ownership", None)
            STAT_SET(
                "membership.epoch", omap0.epoch if omap0 is not None else 0
            )
            STAT_SET(
                "membership.live_ranks",
                len(omap0.live_ranks) if omap0 is not None else tp.n_ranks,
            )
        # set when ownership flipped mid-chain: the next checkpoint save
        # re-anchors with a base (a delta must not straddle an epoch flip)
        self._force_base = False
        # the map the LAST ownership flip replaced: adoption falls back to
        # it when a dead rank's chain predates the flip (it died before
        # its own re-anchor save committed)
        self._prev_ownership = None
        self.round_to = round_to
        self.shrink = shrink
        self.on_give_up = on_give_up
        self._on_poisoned = on_poisoned
        # poisoned pass admitted under the degrade policy: the next
        # begin_pass (and any revert-retry of it) must bypass the gate
        self._admit_poisoned = False
        # default the dataset's dead-letter dir under the durable root so
        # quarantined records live next to the checkpoints they shadow
        if (
            checkpoint is not None
            and getattr(dataset, "quarantine_dir", "absent") is None
        ):
            dataset.quarantine_dir = os.path.join(checkpoint.root, "quarantine")
        # backend bring-up through the watchdog (no-op when jax is already
        # initialized — i.e. in every in-process test — but a cold trainer
        # entrypoint on a wedged TPU falls back to CPU instead of hanging),
        # then the persistent compile cache: "auto" resolves under the
        # durable checkpoint root, next to the checkpoints it warms
        from paddlebox_tpu.utils import backendguard, compilecache

        self.backend_verdict = backendguard.ensure_backend()
        cache_dir = compilecache.resolve_dir(
            str(config.get_flag("compile_cache_dir")),
            ckpt_root=checkpoint.root if checkpoint is not None else None,
        )
        if cache_dir is not None:
            compilecache.enable(cache_dir)
        # telemetry plane: metric series + incident bundles live under the
        # durable checkpoint root (obs/) so postmortems travel with the
        # artifacts they explain; without a checkpoint both stay off
        # unless the obs_incident_dir flag points somewhere explicitly
        self.metrics: Optional[MetricsWriter] = None
        self._incident_dir: Optional[str] = None
        if checkpoint is not None:
            obs_dir = os.path.join(checkpoint.root, "obs")
            rank = getattr(transport, "rank", 0) if transport is not None else 0
            self.metrics = MetricsWriter(obs_dir, rank=rank)
            self._incident_dir = os.path.join(obs_dir, "incidents")
        self.incidents: List[Incident] = []
        self._auc_history: deque = deque(maxlen=self.gates.auc_window)
        self._pass_seq = 0
        self._date: Optional[str] = None
        # (date, tuple(files)) of the pass whose load this supervisor kicked
        # into the dataset's boundary feed stage. The marker doubles as the
        # "set_date already consumed" record: a kicked pass's set_date runs
        # at kick time, so the adopting (or falling-back) run_pass must NOT
        # call it again — pass_id would double-bump and shift the load's
        # sampling/shuffle seeds off the sequential run's.
        self._prefetch: Optional[tuple] = None

    # ---- incident log ----------------------------------------------------

    def _record(self, kind: str, action: str, attempt: int, detail: str = "") -> Incident:
        inc = Incident(
            pass_seq=self._pass_seq,
            date=self._date,
            kind=kind,
            action=action,
            attempt=attempt,
            detail=detail,
        )
        self.incidents.append(inc)
        STAT_ADD("supervisor_incidents")
        # one literal per kind (MON005): the incident vocabulary is closed
        # (Incident.kind docstring), so the metric family stays enumerable
        if kind == "load_error":
            STAT_ADD("supervisor_load_error")
        elif kind == "prefetch_error":
            STAT_ADD("supervisor_prefetch_error")
        elif kind == "data_poisoned":
            STAT_ADD("supervisor_data_poisoned")
        elif kind == "ckpt_save_error":
            STAT_ADD("supervisor_ckpt_save_error")
        elif kind == "peer_abort":
            STAT_ADD("supervisor_peer_abort")
        elif kind == "train_error":
            STAT_ADD("supervisor_train_error")
        elif kind == "escalate_resume":
            STAT_ADD("supervisor_escalate_resume")
        elif kind == "gave_up":
            STAT_ADD("supervisor_gave_up")
        elif kind == "gate_nan":
            STAT_ADD("supervisor_gate_nan")
        elif kind == "gate_auc":
            STAT_ADD("supervisor_gate_auc")
        elif kind == "rank_death":
            STAT_ADD("supervisor_rank_death")
        elif kind == "migrate":
            STAT_ADD("supervisor_migrate")
        elif kind == "migrate_abort":
            STAT_ADD("supervisor_migrate_abort")
        elif kind == "rank_join":
            STAT_ADD("supervisor_rank_join")
        elif kind == "join_abort":
            STAT_ADD("supervisor_join_abort")
        else:  # pragma: no cover - new kinds must be added above
            STAT_ADD("supervisor_other")
        PROFILER.instant(f"supervisor:{kind}", inc.as_dict())
        if kind in _FATAL_INCIDENT_KINDS and action != "degrade":
            # the pass is lost: publish the last N spans + stat snapshot
            # + this incident as an atomic incident-<ts>.json bundle
            FLIGHT_RECORDER.dump(
                f"supervisor_{kind}", detail, dir_path=self._incident_dir
            )
        return inc

    # ---- pieces ----------------------------------------------------------

    def _load_with_retry(self, date: Optional[str], files: Sequence[str]) -> None:
        for attempt in range(self.retry.retries + 1):
            try:
                if date is not None:
                    self.ds.set_date(date)
                self.ds.set_filelist(list(files))
                self.ds.load_into_memory()
                return
            except Exception as e:
                # the fs tier already burned its own retry-until-open
                # budget; reaching here means the input is still missing
                # or the reader died mid-stream
                if attempt >= self.retry.retries:
                    self._record("load_error", "raise", attempt, repr(e))
                    raise PassFailure(
                        f"load failed after {attempt + 1} attempts: {e}"
                    ) from e
                self._record("load_error", "retry", attempt, repr(e))
                self.retry.sleep(self.retry.backoff(attempt + 1))

    def _kick_prefetch(self, date: Optional[str], files: Sequence[str]) -> None:
        """Stage the NEXT pass's load behind the live pass's training.

        Kicks the dataset's boundary feed pipeline — threaded read, key
        premerge, gated host-row prefetch (see BoxPSDataset.
        _stage_boundary_prefetch) — on the preload thread, so by the time
        ``run_pass`` reaches the next pass its data is already staged.
        Opportunistic: any failure here is an incident, never an attempt
        failure — the next ``run_pass`` falls back to a synchronous load.
        Coordinated (multi-rank) runs don't kick: the load there is itself
        a lockstep verdict exchange that must stay on the pass boundary.
        """
        if self.coord is not None or not config.get_flag("boundary_pipeline"):
            return
        key = (date, tuple(files))
        try:
            if date is not None and self._prefetch != key:
                self.ds.set_date(date)
            # marker set as soon as set_date is consumed: even if the kick
            # dies right after, the fallback load must skip set_date
            self._prefetch = key
            self.ds.set_filelist(list(files))
            self.ds.preload_into_memory()
        except Exception as e:
            self._record("prefetch_error", "deferred", 0, repr(e))

    def _adopt_prefetch(self, date: Optional[str], files: Sequence[str]) -> None:
        """Consume (or cancel) a previously kicked prefetch, then ensure the
        pass's data is staged — falling back to the synchronous retrying
        load when the kick failed, was reverted away, or targeted a
        different pass."""
        marker, self._prefetch = self._prefetch, None
        key = (date, tuple(files))
        if marker == key:
            staged = False
            try:
                self.ds.wait_preload_done()
                # a revert (or a failed kick) may have discarded the staged
                # slot after the marker was set — verify before trusting it
                staged = self.ds._staged is not None
            except Exception as e:
                self._record("prefetch_error", "retry", 0, repr(e))
                self.ds.discard_staged()
            if not staged:
                # set_date already consumed at kick time: date=None
                self._load_with_retry(None, files)
            return
        if marker is not None:
            # stale kick — the caller changed the schedule; cancel it
            try:
                self.ds.wait_preload_done()
            except Exception:
                # the staged load is discarded either way, but a failed
                # one is still a failed load: count it, don't erase it
                STAT_ADD("supervisor_stale_preload_errors")
            self.ds.discard_staged()
        self._load_with_retry(date, files)

    @property
    def on_poisoned(self) -> str:
        """Effective poisoned-pass policy (constructor arg wins, else the
        on_poisoned_pass flag)."""
        v = self._on_poisoned or str(config.get_flag("on_poisoned_pass"))
        if v not in ("fail", "skip_pass", "degrade"):
            raise ValueError(
                f"on_poisoned_pass must be fail|skip_pass|degrade, got {v!r}"
            )
        return v

    def _poison_report(self) -> Optional[Dict[str, Any]]:
        """The dataset's admission verdict for the loaded pass (None for
        datasets without the quarantine surface, e.g. test doubles)."""
        rep_fn = getattr(self.ds, "admission_report", None)
        return rep_fn() if rep_fn is not None else None

    def _handle_poisoned(
        self, detail: str, rep: Optional[Dict[str, Any]]
    ) -> bool:
        """Apply the on_poisoned policy to an already-global poison verdict.
        True -> proceed with the pass (degrade), False -> drop it
        (skip_pass); the fail policy raises DataPoisonedError."""
        policy = self.on_poisoned
        loss = ""
        if rep is not None and (rep["bad_lines"] or rep["bad_files"]):
            loss = (
                f" (loss: {rep['bad_lines']} lines / {rep['bad_files']} "
                f"files, line_fraction={rep['line_fraction']:.5f})"
            )
        if policy == "degrade":
            self._record("data_poisoned", "degrade", 0, detail + loss)
            self._admit_poisoned = True
            return True
        if policy == "skip_pass":
            self._record("data_poisoned", "skip", 0, detail + loss)
            drop = getattr(self.ds, "drop_pass_data", None)
            if drop is not None:
                drop()
            return False
        self._record("data_poisoned", "raise", 0, detail + loss)
        raise DataPoisonedError(
            detail, report=rep, dead_letter=(rep or {}).get("dead_letter")
        )

    def _gate(self, out: Dict[str, float]) -> None:
        g = self.gates
        batches = out.get("batches", 0.0)
        if batches:
            ratio = out.get("nan_batches", 0.0) / batches
            if ratio > g.nan_ratio_max:
                raise PassRejected(
                    "nan",
                    f"{ratio:.3f} of batches NaN-skipped "
                    f"(max {g.nan_ratio_max:.3f})",
                )
        auc = out.get("auc")
        if auc is None or not np.isfinite(auc):
            return
        if g.auc_absolute_floor is not None and auc < g.auc_absolute_floor:
            raise PassRejected(
                "auc", f"auc {auc:.4f} under absolute floor {g.auc_absolute_floor:.4f}"
            )
        if len(self._auc_history) >= g.auc_min_history:
            floor = float(np.mean(self._auc_history)) - g.auc_floor_margin
            if auc < floor:
                raise PassRejected(
                    "auc",
                    f"auc {auc:.4f} under trailing floor {floor:.4f} "
                    f"(window of {len(self._auc_history)} confirmed passes)",
                )

    def _attempt(
        self, n_batches: Optional[int], prefetch: Optional[tuple] = None
    ) -> Dict[str, float]:
        """One armed begin->train->gate->[global verdict]->confirm cycle."""
        err: Optional[Exception] = None
        out: Dict[str, float] = {}
        try:
            if not self.ds._in_pass:
                # first attempt, or a revert re-armed the in-memory data.
                # admit_poisoned only reaches datasets that know the kwarg
                # (and only under the degrade policy) — test doubles and
                # older datasets keep their plain signature
                kw = {"admit_poisoned": True} if self._admit_poisoned else {}
                self.ds.begin_pass(
                    round_to=self.round_to, enable_revert=True, trainer=self.tr,
                    **kw,
                )
            self.tr.prepare_pass(self.ds, n_batches)
            if prefetch is not None:
                # training is about to occupy the device: stage the next
                # pass's load/premerge/prefetch behind it
                self._kick_prefetch(prefetch[0], prefetch[1])
            out = self.tr.train_pass(self.ds, n_batches=n_batches)
            # the trained table just landed: kick the host writeback now so
            # it overlaps the gate/verdict window instead of blocking the
            # boundary. Safe pre-verdict — the armed guard's revert covers
            # partial writeback, and revert_pass cancels the kick.
            if hasattr(self.ds, "kick_writeback"):
                self.ds.kick_writeback(self.tr.trained_table())
            self._gate(out)
        except Exception as e:
            if self.coord is None:
                raise
            # hold the local failure until the verdict is published: peers
            # are (or soon will be) waiting on this rank's vote, and only
            # a NO that every rank hears aborts the pass everywhere
            err = e
        if self.coord is not None:
            ok, detail = self.coord.exchange_verdict(
                f"pass:{self._pass_seq}", err is None, repr(err) if err else ""
            )
            if err is not None:
                raise err
            if not ok:
                raise CoordinatedAbort(detail)
        # confirm ONLY after the global verdict: the guard is still armed
        # up to here, so every rank that must revert still can
        # classic (host) writeback: a guard is armed, so the carried-table
        # boundary is gated off anyway — hand over the host copy
        self.ds.end_pass(self.tr.trained_table(), shrink=self.shrink)
        return out

    def _revert(self, attempt: int, cause: BaseException) -> None:
        if isinstance(cause, PassRejected):
            kind = f"gate_{cause.gate}"
        elif isinstance(cause, CoordinatedAbort):
            kind = "peer_abort"
        else:
            kind = "train_error"
        try:
            self.ds.revert_pass()
        except Exception as e:
            # an unrevertable pass (guard lost, revert itself died) can
            # only be healed by the durable tier
            self._record(kind, "revert_failed", attempt, f"{cause!r}; revert: {e!r}")
            raise PassFailure(f"revert failed after {cause!r}: {e}") from e
        self._record(kind, "revert_retry", attempt, repr(cause))

    def _escalate(self, attempt: int, cause: BaseException) -> None:
        """Resume the last durable (manifest-verified) state and re-enter."""
        state = self.checkpoint.resume(self.table, self.tr)
        # external overwrite of table rows + dense params: the trainer's
        # cached device state is stale now
        self.tr._state = None
        self.tr._state_ws = None
        self._record(
            "escalate_resume", "resume", attempt, f"{cause!r} -> resumed {state}"
        )

    def _save_checkpoint(self, mode: str) -> None:
        assert self.checkpoint is not None
        for attempt in range(self.retry.retries + 1):
            try:
                if mode == "base" or self._force_base:
                    # an ownership flip mid-day re-anchors the chain: the
                    # old chain's deltas cover the pre-flip key ranges and
                    # must not be extended across the epoch
                    self.checkpoint.save_base(self._date, self.table, self.tr)
                    self._force_base = False
                else:
                    self.checkpoint.save_delta(self._date, self.table, self.tr)
                return
            except MembershipEpochError as e:
                # belt-and-braces: the cursor says the chain predates this
                # rank's ownership epoch — re-anchor instead of retrying
                # the refused delta
                self._record("ckpt_save_error", "retry", attempt, repr(e))
                self._force_base = True
            except Exception as e:
                # atomic publishing means a failed attempt left nothing
                # under a final name — a retry starts clean
                if attempt >= self.retry.retries:
                    self._record("ckpt_save_error", "raise", attempt, repr(e))
                    raise PassFailure(
                        f"checkpoint {mode} save failed after "
                        f"{attempt + 1} attempts: {e}"
                    ) from e
                self._record("ckpt_save_error", "retry", attempt, repr(e))
                self.retry.sleep(self.retry.backoff(attempt + 1))
        raise PassFailure(
            f"checkpoint {mode} save failed: retry budget exhausted "
            "re-anchoring across an ownership-epoch flip"
        )

    # ---- elastic membership ---------------------------------------------

    def _ownership_map(self):
        """The dataset's current OwnershipMap, defaulting to the even
        split over all transport ranks (epoch 0) when none was installed
        yet — identical to what DistributedWorkingSet defaults to."""
        omap = getattr(self.ds, "ownership", None)
        if omap is None:
            omap = _membership.OwnershipMap.even(
                self.ds.n_mesh_shards, self.coord.transport.n_ranks
            )
        return omap

    def _install_ownership(self, new_map, prev_map=None) -> None:
        """Atomically adopt a successor OwnershipMap: dataset routing,
        checkpoint epoch, and the chain re-anchor flip together.

        The re-anchor base save happens HERE, before any training resumes
        under the new map — not at the next pass boundary. Deferring it
        opens a window where a rank that dies mid-pass leaves a chain
        predating the flip: shard ranges it gained in the flip would be
        absent from (or stale in) that chain, and adoption would silently
        restore them from the seeded init. A rank whose re-anchor save
        itself fails raises (PassFailure after retries) and is shrunk out
        by the survivors, whose adoption then uses the previous owners'
        chains for its un-anchored gained ranges (``_prev_ownership``).

        ``prev_map`` overrides what is recorded as the map this flip
        replaced — the membership round passes its SYNCED base so every
        survivor records the same predecessor, even one that re-entered
        the round a map behind its peers."""
        self._prev_ownership = (
            prev_map if prev_map is not None else self._ownership_map()
        )
        self.ds.ownership = new_map
        if self.checkpoint is not None:
            self.checkpoint.ownership_epoch = new_map.epoch
            self.checkpoint.live_ranks = [int(r) for r in new_map.live_ranks]
        self._force_base = True
        STAT_SET("membership.epoch", new_map.epoch)
        STAT_SET("membership.live_ranks", len(new_map.live_ranks))
        if self.checkpoint is not None and self._date is not None:
            self._save_checkpoint("base")

    def _handle_rank_death(self, e: PeerDeadError) -> None:
        """Survivor-side membership change: verdict round -> map sync ->
        shrunk map -> shard adoption from the dead ranks' durable
        checkpoint shards.

        Re-entrant under further deaths: a peer dying WHILE the round runs
        surfaces as a nested PeerDeadError from any of its collectives;
        rather than killing the day, the new evidence is unioned into the
        dead set and the whole round re-runs from the refreshed set —
        bounded by the rank count, since each re-entry strictly grows it.

        On return the retried pass runs on the survivors over exactly the
        table state a fresh shrunk-membership run would hold (adoption is
        an idempotent upsert from the last pass boundary, and keys never
        checkpointed are recreated from the seeded init — both bitwise-
        equal to the fresh run, pinned by tests/test_elastic.py)."""
        assert self.elastic is not None and self.coord is not None
        tp = self.coord.transport
        last = e
        for round_no in range(tp.n_ranks + 1):
            tp.mark_dead(last.dead)
            try:
                self._membership_round(last)
                return
            except PeerDeadError as nested:
                last = nested
                self._record(
                    "rank_death", "retry", round_no,
                    f"peer died mid-membership-round: {nested!r}",
                )
        raise PassFailure(
            f"membership change did not converge within {tp.n_ranks + 1} "
            f"rounds; last evidence: {last!r}"
        ) from last

    def _membership_round(self, e: PeerDeadError) -> None:
        """One attempt of the membership change; raises PeerDeadError when
        yet another peer dies mid-round (caller unions and re-enters)."""
        tp = self.coord.transport
        # revert anything the dying attempt armed before touching the table
        if getattr(self.ds, "_in_pass", False):
            try:
                self.ds.revert_pass()
            except Exception as re_err:
                self._record(
                    "rank_death", "revert_failed", 0,
                    f"{e!r}; revert: {re_err!r}",
                )
                raise PassFailure(
                    f"revert failed after peer death {e!r}: {re_err}"
                ) from re_err
        self.coord.advance(getattr(self.ds, "pass_epoch", None))
        # membership verdict round: every survivor converges on one dead
        # set (the proposal is encoded in the collective tag)
        agreed = _membership.agree_membership(
            tp, self._pass_seq, timeout=self.elastic.member_timeout
        )
        # map sync: a survivor whose PREVIOUS round was cut short by this
        # death re-enters one map behind its peers; all derive the
        # successor from the highest-epoch base so epochs and boundaries
        # agree everywhere (divergent same-epoch maps raise — split-brain)
        old_map = self._ownership_map()
        base_map = _membership.sync_map(
            tp, self._pass_seq, agreed, old_map,
            timeout=self.elastic.member_timeout,
        )
        # adoption sources are judged against MY installed map: a rank
        # that missed an intermediate flip never adopted its pieces, so
        # for it each dead rank's range is the wider pre-flip one
        newly_dead = [d for d in agreed if old_map.is_live(d)]
        new_map = base_map.shrink(agreed)
        my_rank = tp.rank
        adopted_ranges = []
        for d in newly_dead:
            dlo, dhi = old_map.range_of(d)
            mlo, mhi = new_map.range_of(my_rank)
            lo, hi = max(dlo, mlo), min(dhi, mhi)
            if lo < hi:
                adopted_ranges.append([lo, hi])
        # adoption: bounded retries in ISOLATION — the pass must not retry
        # under a half-installed map (keys routed to a dead owner would
        # silently vanish from the exchange)
        adopt_err: Optional[Exception] = None
        adopted_keys = 0
        for a in range(self.elastic.adopt_retries + 1):
            try:
                adopted_keys = sum(
                    _membership.adopt_dead_shards(
                        self.table, self.elastic.shared_root, d,
                        old_map, new_map, my_rank,
                        prev_map=self._prev_ownership,
                    )
                    for d in newly_dead
                )
                adopt_err = None
                break
            except Exception as ae:
                adopt_err = ae
                if a < self.elastic.adopt_retries:
                    self._record("rank_death", "retry", a, repr(ae))
                    self.retry.sleep(self.retry.backoff(a + 1))
        # every survivor must finish adopting before anyone re-enters the
        # pass — and one survivor failing adoption aborts all (the dead
        # ranges would be served by nobody). The tag carries the successor
        # map's epoch AND content fingerprint: post-sync these are
        # identical everywhere, so a mismatch can only mean a protocol
        # bug — it stalls loudly instead of committing divergent maps.
        ok, detail = self.coord.exchange_verdict(
            f"member:{self._pass_seq}:{new_map.epoch}:{new_map.fingerprint()}",
            adopt_err is None,
            repr(adopt_err) if adopt_err else "",
        )
        if adopt_err is not None:
            self._record("rank_death", "raise", 0, repr(adopt_err))
            raise PassFailure(
                f"shard adoption failed after {self.elastic.adopt_retries + 1} "
                f"attempts: {adopt_err}"
            ) from adopt_err
        if not ok:
            self._record("rank_death", "raise", 0, detail)
            raise PassFailure(f"peer shard adoption failed: {detail}")
        self._install_ownership(new_map, prev_map=base_map)
        self._record(
            "rank_death", "revert_retry", 0,
            f"dead={list(agreed)} survivors={list(new_map.live_ranks)} "
            f"ownership_epoch={new_map.epoch} adopted_keys={adopted_keys}",
        )
        bundle = {
            "dead": [int(d) for d in agreed],
            "survivors": [int(r) for r in new_map.live_ranks],
            "ownership_epoch": new_map.epoch,
            "adopted_ranges": adopted_ranges,
            "adopted_keys": int(adopted_keys),
        }
        FLIGHT_RECORDER.note_incident("membership_change", bundle)
        FLIGHT_RECORDER.dump(
            "rank_death", json.dumps(bundle), dir_path=self._incident_dir
        )
        PROFILER.instant("supervisor:membership_change", bundle)

    def _gather_shard_loads(
        self, omap, hot: bool, tag: str
    ) -> np.ndarray:
        """Allgather the global per-mesh-shard load vector under ``omap``.

        Each live rank contributes exactly its owned slice as little-
        endian float64 (8 bytes/shard). ``hot=False`` counts raw owned
        keys; ``hot=True`` weighs them by the Parallax-style hotness
        prior — tiered residency + decayed show counts, computed by
        table/dist_ws.hot_shard_loads — so planners move traffic, not
        tombstone mass. Either way the vector is deterministic from the
        boundary's table state, so every rank derives the identical plan
        from the identical gather."""
        from paddlebox_tpu.table.sparse_table import key_to_shard

        tp = self.coord.transport
        # the carried device table may hold rows the host store lags on —
        # planners read host rows, so everything owed must land first
        drain = getattr(self.table, "drain_pending", None)
        if drain is not None:
            drain()
        lo, hi = omap.range_of(tp.rank)
        if hot:
            from paddlebox_tpu.table.dist_ws import hot_shard_loads

            local = hot_shard_loads(self.table, omap, tp.rank)
        else:
            keys = self.table.keys()
            sh = key_to_shard(keys, omap.n_mesh_shards)
            mine = sh[(sh >= lo) & (sh < hi)]
            local = np.bincount(mine - lo, minlength=hi - lo).astype(
                np.float64
            )
        views = tp.allgather(
            local.astype("<f8").tobytes(), tag,
            timeout=self.elastic.member_timeout,
        )
        loads = np.zeros(omap.n_mesh_shards, np.float64)
        for r in omap.live_ranks:
            rlo, rhi = omap.range_of(r)
            v = views[r]
            if len(v) != (rhi - rlo) * 8:
                # never recut from a silently zero-filled view: the plan
                # would be deterministic (all ranks see the same garbage)
                # yet systematically wrong
                STAT_ADD("membership.load_view_errors")
                raise RuntimeError(
                    f"load view from rank {r} has {len(v)} bytes, expected "
                    f"{(rhi - rlo) * 8} for shard range [{rlo},{rhi})"
                )
            loads[rlo:rhi] = np.frombuffer(v, dtype="<f8")
        return loads

    def _maybe_migrate(self) -> None:
        """Planned migration at a confirmed pass boundary: recut ownership
        boundaries when per-rank key-load skew crosses the threshold and
        stream the moving shard ranges owner->owner. Atomic at the
        boundary: receivers stage, a commit verdict decides, and only a
        global YES flips the epoch — any failure leaves the old epoch
        serving and the plan is re-derived at the next boundary."""
        assert self.elastic is not None and self.coord is not None
        tp = self.coord.transport
        omap = self._ownership_map()
        if len(omap.live_ranks) < 2:
            return
        loads = self._gather_shard_loads(
            omap, self.elastic.hot_migrate,
            f"ctl:load:{self._pass_seq}@e{self.coord.epoch}",
        )
        new_map = _membership.plan_rebalance(
            omap, loads, self.elastic.migrate_skew
        )
        if new_map is None:
            # every rank derived None from the identical global vector —
            # no verdict round needed for a unanimous no-op
            return
        seq = f"{self._pass_seq}.{new_map.epoch}"
        xfer = None
        xfer_err: Optional[Exception] = None
        try:
            xfer = _membership.migrate_ranges(
                tp, self.table, omap, new_map, seq, self.coord.epoch,
                timeout=self.elastic.member_timeout,
            )
        except Exception as me:
            xfer_err = me
        # the commit verdict must be ATOMIC: a rank whose verdict round
        # merely times out cannot tell whether peers committed, so folding
        # the timeout into a local "no" would leave it on the old map while
        # peers flip — colliding epoch numbers over divergent boundaries.
        # fatal=True makes local transport failure here raise instead; this
        # rank dies with PassFailure and the survivors shrink it out. The
        # tag carries the successor map's content fingerprint so bases that
        # diverged for any other reason stall loudly rather than commit.
        try:
            ok, detail = self.coord.exchange_verdict(
                f"migrate:{seq}:{new_map.fingerprint()}",
                xfer_err is None,
                repr(xfer_err) if xfer_err else "",
                fatal=True,
            )
        except PeerDeadError:
            raise  # a DEAD peer is decidable — membership handling owns it
        except (OSError, TimeoutError) as ve:
            STAT_ADD("membership.migrations_aborted")
            self._record("migrate_abort", "raise", 0, repr(ve))
            raise PassFailure(
                f"migrate commit verdict uncertain (transport failure "
                f"mid-round): {ve!r}"
            ) from ve
        if not ok or xfer_err is not None:
            # old epoch still serves; staged pieces are discarded and the
            # plan is re-derived at the next boundary (FLT008 contract)
            STAT_ADD("membership.migrations_aborted")
            self._record(
                "migrate_abort", "retry", 0,
                detail or repr(xfer_err),
            )
            return
        _membership.commit_staged(self.table, xfer["staged"])
        self._install_ownership(new_map)
        STAT_ADD("membership.migrated_keys", int(xfer["recv_keys"]))
        STAT_ADD("membership.migration_bytes", int(xfer["sent_bytes"]))
        self._record(
            "migrate", "commit", 0,
            f"ownership_epoch={new_map.epoch} moves={xfer['moves']} "
            f"recv_keys={xfer['recv_keys']} sent_bytes={xfer['sent_bytes']}",
        )
        FLIGHT_RECORDER.note_incident(
            "migration", {
                "ownership_epoch": new_map.epoch,
                "moves": xfer["moves"],
                "recv_keys": int(xfer["recv_keys"]),
                "sent_bytes": int(xfer["sent_bytes"]),
            },
        )

    # ---- elastic grow: the join protocol --------------------------------

    def _boundary_elastic(self, publishing: bool) -> None:
        """One elastic action per confirmed pass boundary, the autoscale
        loop's decision point: admit a waiting joiner if the policy allows
        (and the chain it must catch up from is being published), else
        consider a planned hot-range migration. One action, not both — an
        admission already recut ownership at this boundary, and the next
        boundary re-derives skew under the grown map."""
        admitted = False
        if publishing:
            admitted = self._maybe_admit_joiner()
        if not admitted and self.elastic.migrate_skew > 1.0:
            self._maybe_migrate()

    def _maybe_admit_joiner(self) -> bool:
        """Boundary scan of the grow half: look for announce knocks from
        non-live ranks, converge the fleet on ONE joiner, and run the
        admission round. The scan rides an allgather and admits only the
        INTERSECTION of what every live rank saw — a knock still in
        flight to some peer admits at the next boundary instead of
        splitting the fleet. Returns True when a joiner was committed."""
        assert self.elastic is not None and self.coord is not None
        tp = self.coord.transport
        omap = self._ownership_map()
        pend = tp.pending_sources(_JOIN_ANNOUNCE_TAG)
        waiting = [int(r) for r in pend if not omap.is_live(r)]
        # consume the knocks now that they're counted: a waiting joiner
        # re-announces every few hundred ms, and unconsumed frames from an
        # already-admitted (or policy-refused) rank must not pile up
        for r in pend:
            while r in tp.pending_sources(_JOIN_ANNOUNCE_TAG):
                tp.recv(_JOIN_ANNOUNCE_TAG, r, timeout=1.0)
        views = tp.allgather(
            json.dumps(waiting).encode(),
            f"ctl:joinscan:{self._pass_seq}@e{self.coord.epoch}",
            timeout=self.elastic.member_timeout,
        )
        common: Optional[set] = None
        for r in omap.live_ranks:
            seen = set(json.loads(views[r].decode() or "[]"))
            common = seen if common is None else (common & seen)
        if not common:
            return False
        if (
            self.elastic.target_ranks is not None
            and len(omap.live_ranks) >= self.elastic.target_ranks
        ):
            # at (or above) the autoscale target: leave announcers waiting
            return False
        return self._admit_joiner(min(common), omap)

    def _admit_joiner(self, joiner: int, omap) -> bool:
        """Survivor side of one admission round.

        Hot loads are gathered among the CURRENT live set (the joiner
        owns nothing and has nothing to vote with yet), the successor map
        carves the joiner its quantile cuts, the lowest live rank
        sponsors the offer, and the ceding flanks stream their ranges
        through the staged ``migrate_ranges`` path. The commit verdict
        composes with the death invariants: the JOINER dying mid-round
        aborts the join cleanly at the old epoch (no shrink — the fleet
        never grew); a SURVIVOR dying aborts the join and re-raises so
        the caller's death handler runs the shrink."""
        tp = self.coord.transport
        loads = self._gather_shard_loads(
            omap, True, f"ctl:jload:{self._pass_seq}@e{self.coord.epoch}"
        )
        new_map = omap.grow(joiner, loads)
        planned = [
            [int(lo), int(hi)]
            for lo, hi, _src, dst in _membership.plan_moves(omap, new_map)
            if dst == joiner
        ]
        seq = f"{self._pass_seq}.{new_map.epoch}"
        # readmit BEFORE any collective that counts the joiner's slot.
        # Deliberately after the load gather: mark_alive keeps the link's
        # seq space (transport docstring), and a genuinely new incarnation
        # already reset its inbound counter at HELLO.
        tp.mark_alive(joiner)
        if tp.rank == min(omap.live_ranks):
            # one sponsor hands the joiner everything it needs to sync:
            # both maps, the day/pass clocks, and the pass epoch its
            # frames must carry
            offer = {
                "old_map": omap.to_json(),
                "new_map": new_map.to_json(),
                "date": self._date,
                "pass_seq": self._pass_seq,
                "pass_epoch": self.coord.epoch,
            }
            tp.send(
                joiner, f"{_JOIN_OFFER_TAG}:{joiner}",
                json.dumps(offer).encode(),
            )
        join_err: Optional[Exception] = None
        xfer = None
        try:
            xfer = _membership.migrate_ranges(
                tp, self.table, omap, new_map, seq, self.coord.epoch,
                timeout=self.elastic.member_timeout,
            )
        except Exception as me:
            join_err = me
        try:
            ok, detail = self.coord.exchange_verdict(
                f"join:{seq}:{new_map.fingerprint()}",
                join_err is None,
                repr(join_err) if join_err else "",
                fatal=True,
            )
        except PeerDeadError as e:
            tp.mark_dead([joiner])
            if set(int(d) for d in e.dead) <= {int(joiner)}:
                # ONLY the joiner died mid-join: clean local abort, the
                # fleet stays at the old epoch — no shrink round runs
                # because membership never actually grew
                self._join_abort(
                    joiner, new_map, planned, f"joiner died mid-join: {e!r}"
                )
                return False
            # a SURVIVOR died during the join: abort it, then let the
            # caller's death handler run the shrink over the old map
            self._join_abort(joiner, new_map, planned, repr(e))
            raise
        except (OSError, TimeoutError) as ve:
            # commit-point uncertainty: same contract as migrate — die
            # loudly rather than guess which side of the flip peers took
            self._join_abort(joiner, new_map, planned, repr(ve))
            raise PassFailure(
                f"join commit verdict uncertain (transport failure "
                f"mid-round): {ve!r}"
            ) from ve
        if not ok or join_err is not None:
            # the joiner (or a ceding flank) voted NO: nothing was
            # committed anywhere — receivers only staged — so the old
            # epoch keeps serving bitwise and the joiner may re-announce
            tp.mark_dead([joiner])
            self._join_abort(
                joiner, new_map, planned,
                detail if join_err is None else repr(join_err),
            )
            return False
        _membership.commit_staged(self.table, xfer["staged"])
        self._install_ownership(new_map, prev_map=omap)
        STAT_ADD("membership.joins_total")
        self._record(
            "rank_join", "commit", 0,
            f"joiner={int(joiner)} ownership_epoch={new_map.epoch} "
            f"planned_ranges={planned} sent_keys={xfer['sent_keys']}",
        )
        bundle = {
            "joiner": int(joiner),
            "live": [int(r) for r in new_map.live_ranks],
            "ownership_epoch": int(new_map.epoch),
            "planned_ranges": planned,
            "sent_keys": int(xfer["sent_keys"]),
        }
        FLIGHT_RECORDER.note_incident("rank_join", bundle)
        PROFILER.instant("supervisor:rank_join", bundle)
        return True

    def _join_abort(self, joiner: int, new_map, planned, reason) -> None:
        """Abort bookkeeping for a failed or refused admission. Nothing
        was committed (receivers only staged), so the fleet stays at the
        OLD epoch bitwise; the incident bundle — joiner rank, the ranges
        it would have taken, the epoch that never happened, and why —
        lands under <ckpt>/obs/incidents for the postmortem."""
        bundle = {
            "joiner": int(joiner),
            "planned_ranges": [[int(lo), int(hi)] for lo, hi in planned],
            "ownership_epoch": int(new_map.epoch),
            "reason": str(reason),
        }
        STAT_ADD("membership.joins_aborted")
        self._record("join_abort", "retry", 0, json.dumps(bundle))
        FLIGHT_RECORDER.note_incident("join_abort", bundle)
        FLIGHT_RECORDER.dump(
            "join_abort", json.dumps(bundle), dir_path=self._incident_dir
        )
        PROFILER.instant("supervisor:join_abort", bundle)

    # ---- elastic grow: the joiner side -----------------------------------

    def _announce_join(self) -> None:
        """Best-effort knock on every potential sponsor. Fires the
        ``membership.join_announce`` fault site (FLT008: an injected
        failure aborts nothing durable — the announce is simply retried).
        Unreachable peers are expected — the announcer does not know who
        is live; the survivors' scan intersects what actually arrived."""
        tp = self.coord.transport
        _fault_fire("membership.join_announce")
        for dst in range(tp.n_ranks):
            if dst == tp.rank or tp.is_marked_dead(dst):
                continue
            try:
                tp.send(dst, _JOIN_ANNOUNCE_TAG, b"")
            # a knock bouncing off a dead or not-yet-up peer is the
            # normal case — the announcer re-knocks every ~250ms and
            # the survivors' scan intersects what actually arrived
            # pbox-lint: disable=EXC007
            except (ConnectionError, OSError):
                continue

    def _await_offer(self, deadline: float) -> Optional[Dict[str, Any]]:
        """Announce (re-announcing every ~250ms) until a sponsor's offer
        arrives; None on deadline. Every queued offer is consumed and the
        NEWEST wins — a stale offer from an earlier aborted round must
        not shadow the live one (its maps would fingerprint-mismatch the
        fleet's verdict tag and stall the round out)."""
        tp = self.coord.transport
        tag = f"{_JOIN_OFFER_TAG}:{tp.rank}"
        last_announce = -1.0
        while True:
            now = time.monotonic()
            if now >= deadline:
                return None
            if now - last_announce >= 0.25:
                self._announce_join()
                last_announce = now
            payload = None
            srcs = tp.pending_sources(tag)
            while srcs:
                for s in srcs:
                    payload = tp.recv(tag, s, timeout=1.0)
                srcs = tp.pending_sources(tag)
            if payload is not None:
                return json.loads(payload.decode())
            time.sleep(0.02)

    def _catch_up(self, old_map, new_map) -> Dict[str, Any]:
        """Serve-follower catch-up: rebuild the gained ranges from the
        ceding owners' PUBLISHED base+delta chains — the Follower's CRC-
        verified chain apply (serve/follower.apply_published_chain),
        including mid-chain epoch re-anchors: a valid watermark is always
        single-epoch (validate_watermark rejects straddles), so a chain
        that re-anchored mid-day is simply read from its newest base.

        Returns per-piece (keys, rows) in ``plan_moves`` order — aligned
        1:1 with what ``migrate_ranges`` stages — plus the ceding owners'
        decay-epoch clock. Fires ``membership.catchup_apply`` once per
        ceding source (FLT008: an injected failure aborts the join at the
        OLD epoch — nothing was committed — and a retried join
        succeeds)."""
        from paddlebox_tpu.serve.follower import apply_published_chain
        from paddlebox_tpu.table.sparse_table import (
            HostSparseTable,
            key_to_shard,
        )

        me = self.coord.transport.rank
        pieces = [
            (lo, hi, src)
            for lo, hi, src, dst in _membership.plan_moves(old_map, new_map)
            if dst == me
        ]
        scratches: Dict[int, Any] = {}
        decay_epochs = 0
        keys_by_piece: List[np.ndarray] = []
        rows_by_piece: List[np.ndarray] = []
        for lo, hi, src in pieces:
            if src not in scratches:
                _fault_fire("membership.catchup_apply")
                scratch = HostSparseTable(
                    self.table.layout, self.table.opt,
                    n_shards=self.table.n_shards,
                )
                state = apply_published_chain(
                    rank_root(self.elastic.shared_root, src), scratch
                )
                if state is None:
                    raise RuntimeError(
                        f"ceding rank {src} has no published chain under "
                        f"{self.elastic.shared_root!r} — cannot catch up"
                    )
                scratches[src] = scratch
                decay_epochs = max(
                    decay_epochs, getattr(scratch, "decay_epochs", 0)
                )
            scratch = scratches[src]
            keys = np.sort(scratch.keys())
            sh = key_to_shard(keys, old_map.n_mesh_shards)
            sel = keys[(sh >= lo) & (sh < hi)]
            keys_by_piece.append(sel)
            rows_by_piece.append(
                scratch.pull_or_create(sel)
                if len(sel)
                else np.zeros((0, self.table.layout.width), np.float32)
            )
        return {
            "keys_by_piece": keys_by_piece,
            "rows_by_piece": rows_by_piece,
            "decay_epochs": int(decay_epochs),
            "keys": int(sum(len(k) for k in keys_by_piece)),
        }

    def _verify_catchup(self, catchup: Dict[str, Any], staged) -> None:
        """Bitwise cross-check, chain vs wire: at a published boundary
        the ceding owner's chain IS its table state, so the rows the
        joiner rebuilt from disk must equal the rows it was streamed —
        any divergence means a torn chain or a protocol bug, and the join
        must abort (the migrated copy is never trusted on faith)."""
        if len(staged) != len(catchup["keys_by_piece"]):
            raise RuntimeError(
                f"catch-up derived {len(catchup['keys_by_piece'])} pieces "
                f"but the transfer staged {len(staged)}"
            )
        for i, (mkeys, mrows) in enumerate(staged):
            ckeys = catchup["keys_by_piece"][i]
            crows = catchup["rows_by_piece"][i]
            if not (
                np.array_equal(mkeys, ckeys) and np.array_equal(mrows, crows)
            ):
                raise RuntimeError(
                    f"catch-up/transfer divergence on piece {i}: the "
                    "published chain and the live migration disagree "
                    f"({len(ckeys)} chain keys vs {len(mkeys)} wire keys)"
                )

    def _join_attempt(self, offer: Dict[str, Any]) -> bool:
        """One admission attempt from a sponsor's offer (joiner side).

        Sync the fleet's clocks, mark the ranks the successor map says
        are dead, catch up from the published chains, receive the staged
        transfer, cross-check the two bitwise, then vote in the commit
        round. Once the offer is consumed this rank MUST vote — peers
        block on its verdict slot, so every local failure (including a
        dead ceding peer) folds into a NO vote rather than a silent bail;
        only the verdict exchange itself failing abandons the round."""
        tp = self.coord.transport
        me = tp.rank
        old_map = _membership.OwnershipMap.from_json(offer["old_map"])
        new_map = _membership.OwnershipMap.from_json(offer["new_map"])
        # adopt the fleet's clocks BEFORE any collective: verdict tags are
        # scoped by pass_seq and pass epoch
        self._pass_seq = int(offer["pass_seq"])
        self._date = offer["date"]
        epoch = int(offer["pass_epoch"])
        self.coord.epoch = epoch
        if hasattr(self.ds, "pass_epoch"):
            self.ds.pass_epoch = epoch
        tp.discard_epochs_below(epoch)
        dead = [
            r for r in range(tp.n_ranks)
            if r != me and not new_map.is_live(r)
        ]
        if dead:
            tp.mark_dead(dead)
        seq = f"{self._pass_seq}.{new_map.epoch}"
        planned = [
            [int(lo), int(hi)]
            for lo, hi, _src, dst in _membership.plan_moves(old_map, new_map)
            if dst == me
        ]
        join_err: Optional[Exception] = None
        xfer = None
        catchup = None
        try:
            catchup = self._catch_up(old_map, new_map)
            xfer = _membership.migrate_ranges(
                tp, self.table, old_map, new_map, seq, epoch,
                timeout=self.elastic.member_timeout,
            )
            self._verify_catchup(catchup, xfer["staged"])
        except Exception as e:
            # includes PeerDeadError: peers still block on this slot's
            # verdict, so fold the failure into a NO vote
            join_err = e
        try:
            ok, detail = self.coord.exchange_verdict(
                f"join:{seq}:{new_map.fingerprint()}",
                join_err is None,
                repr(join_err) if join_err else "",
                fatal=True,
            )
        except PeerDeadError as e:
            # the fleet itself lost a rank mid-round: the survivors will
            # shrink and re-offer; go back to announcing
            tp.mark_dead(e.dead)
            self._record(
                "join_abort", "retry", 0, f"sponsor fleet lost a rank: {e!r}"
            )
            return False
        except (OSError, TimeoutError) as ve:
            raise PassFailure(
                f"join commit verdict uncertain (transport failure "
                f"mid-round): {ve!r}"
            ) from ve
        if not ok or join_err is not None:
            self._join_abort(
                me, new_map, planned,
                detail if join_err is None else repr(join_err),
            )
            return False
        _membership.commit_staged(self.table, xfer["staged"])
        if catchup["decay_epochs"] and not getattr(
            self.table, "decay_epochs", 0
        ):
            # the carved rows' decay clock must match their previous
            # owner's, or the first decay after the join drifts off a
            # fresh fixed-size run
            self.table.decay_epochs = catchup["decay_epochs"]
        self._install_ownership(new_map, prev_map=old_map)
        STAT_ADD("membership.joins_total")
        self._record(
            "rank_join", "commit", 0,
            f"joiner={me} ownership_epoch={new_map.epoch} "
            f"recv_keys={xfer['recv_keys']} catchup_keys={catchup['keys']}",
        )
        bundle = {
            "joiner": int(me),
            "live": [int(r) for r in new_map.live_ranks],
            "ownership_epoch": int(new_map.epoch),
            "planned_ranges": planned,
            "recv_keys": int(xfer["recv_keys"]),
            "catchup_keys": int(catchup["keys"]),
        }
        FLIGHT_RECORDER.note_incident("rank_join", bundle)
        PROFILER.instant("supervisor:rank_join", bundle)
        return True

    def join_day(
        self,
        pass_files: Sequence[Sequence[str]],
        n_batches: Optional[int] = None,
        publish: bool = True,
        timeout: float = 60.0,
    ) -> List[Optional[Dict[str, float]]]:
        """JOINER-side day entrypoint: the grow dual of ``run_day``.

        Announce -> await a sponsor's offer -> catch up from the ceding
        owners' published base+delta chains (the serve follower's CRC-
        verified chain apply) -> receive the carved ranges through the
        staged migrate path -> global fingerprint-tagged commit verdict
        -> durable base re-anchor (``_install_ownership``) -> run the
        REMAINING passes of the day in lockstep with the fleet. An
        aborted admission (injected fault mid-catch-up, a refused
        verdict, a survivor death mid-round) leaves the fleet at the old
        epoch bitwise and this rank simply re-announces; ``timeout``
        bounds the total wait for admission.

        Saves are always deltas: the admission itself re-anchored a base
        at the new epoch, so the joiner's chain starts there and
        ``save_delta``'s refuse-to-straddle rule is satisfied by
        construction."""
        if self.elastic is None or self.coord is None:
            raise ValueError(
                "join_day requires elastic mode and a coordinated transport"
            )
        deadline = time.monotonic() + timeout
        while True:
            if time.monotonic() >= deadline:
                raise PassFailure(
                    f"rank {self.coord.transport.rank} was not admitted "
                    f"within {timeout:.1f}s"
                )
            try:
                offer = self._await_offer(deadline)
                if offer is None:
                    continue
                if self._join_attempt(offer):
                    break
            except InjectedFault as e:
                # an injected announce/catch-up fault is retryable: note
                # it and knock again (FLT008 recovery contract)
                self._record("join_abort", "retry", 0, repr(e))
            self.retry.sleep(0.01)
        outs: List[Optional[Dict[str, float]]] = []
        do_save = publish and self.checkpoint is not None
        start = self._pass_seq
        for p in range(start, len(pass_files)):
            files = pass_files[p]
            nxt = (
                (self._date, tuple(pass_files[p + 1]))
                if p + 1 < len(pass_files)
                else None
            )
            outs.append(
                self.run_pass(
                    files, date=self._date, n_batches=n_batches,
                    save="delta" if do_save else None, prefetch=nxt,
                )
            )
            try:
                self._boundary_elastic(do_save)
            except PeerDeadError as e:
                self._handle_rank_death(e)
            if self.metrics is not None:
                self.metrics.maybe_snapshot()
        return outs

    # ---- the supervised pass --------------------------------------------

    def run_pass(
        self,
        files: Sequence[str],
        date: Optional[str] = None,
        n_batches: Optional[int] = None,
        save: Optional[str] = None,  # None | "base" | "delta"
        prefetch: Optional[tuple] = None,  # (date, files) of the NEXT pass
    ) -> Optional[Dict[str, float]]:
        """Load, train, gate, and publish one pass, healing failures.

        ``prefetch`` names the pass that follows this one: once training is
        underway its load is kicked into the dataset's boundary feed stage,
        and the next ``run_pass`` over the same (date, files) adopts the
        staged result instead of loading synchronously (``run_day`` threads
        this automatically).

        Returns the pass metrics, or None when the pass was dropped
        (``on_give_up="skip"`` after retries AND escalation failed).
        """
        if save not in (None, "base", "delta"):
            raise ValueError(f"save must be None, 'base' or 'delta', got {save!r}")
        if save is not None and self.checkpoint is None:
            raise ValueError("save requires a CheckpointManager")
        self._pass_seq += 1
        self._date = date if date is not None else self._date
        self._admit_poisoned = False
        pass_t0 = time.monotonic()
        if self.coord is None:
            self._adopt_prefetch(date, files)
        else:
            # coordinate the load the same way as the pass verdict: a rank
            # whose input never materialized must take every peer down with
            # it NOW, not leave them hanging in the first exchange
            while True:
                load_err: Optional[PassFailure] = None
                try:
                    self._load_with_retry(date, files)
                except PassFailure as e:
                    load_err = e
                try:
                    ok, detail = self.coord.exchange_verdict(
                        f"load:{self._pass_seq}",
                        load_err is None,
                        repr(load_err) if load_err else "",
                    )
                except PeerDeadError as e:
                    # only raised in elastic mode: shrink membership and
                    # redo the (unarmed) load on the survivors
                    if self.elastic is None:
                        raise
                    self._handle_rank_death(e)
                    continue
                break
            if load_err is not None:
                raise load_err
            if not ok:
                # nothing armed yet — no revert, just a clean global stop
                self._record("peer_abort", "raise", 0, detail)
                raise PassFailure(
                    f"pass {self._pass_seq} aborted: peer load failed: {detail}"
                )
        # poison-aware admission: DataPoisonedError is DETERMINISTIC — the
        # same filelist replays the same corruption on every attempt, so it
        # is resolved here, before the retry loop, under the on_poisoned
        # policy. In coordinated runs the verdict rides the same allgather
        # as the pass/load verdicts so every rank admits or rejects in
        # lockstep (one rank degrading a pass its peer re-runs clean would
        # desync the working-set exchange).
        rep = self._poison_report()
        poisoned = rep is not None and rep["poisoned"]
        poison_detail = rep["detail"] if poisoned else ""
        if self.coord is not None and rep is not None:
            ok, gdetail = self.coord.exchange_verdict(
                f"poison:{self._pass_seq}", not poisoned, poison_detail
            )
            if not ok and not poisoned:
                poisoned = True
                poison_detail = f"peer pass data poisoned: {gdetail}"
        if poisoned and not self._handle_poisoned(poison_detail, rep):
            return None
        escalated = False
        attempt = 0
        while True:
            try:
                with PROFILER.record_event("supervised_pass_attempt", "supervisor"):
                    out = self._attempt(n_batches, prefetch=prefetch)
                break
            except DataPoisonedError as e:
                # belt-and-braces: the pre-loop check above resolves poison
                # before anything is armed, so reaching here means the
                # thresholds/policy changed under a live attempt. Still
                # deterministic — never burn backoff retries on it.
                self._record("data_poisoned", "raise", attempt, repr(e))
                raise
            except PeerDeadError as e:
                if self.elastic is None or self.coord is None:
                    # hardware loss without elastic membership stays what
                    # it always was: terminal for the day
                    raise
                # membership event, not a pass failure: verdict round,
                # ownership shrink, adoption — then retry the pass on the
                # survivors with a FRESH budget (the hardware loss costs
                # one pass retry, never the day)
                self._handle_rank_death(e)
                attempt = 0
                escalated = False
                continue
            except Exception as e:
                self._revert(attempt, e)
                if self.coord is not None:
                    # revert_pass bumped ds.pass_epoch; adopt it (or bump
                    # our own for datasets without the counter) and purge
                    # the aborted attempt's in-flight frames
                    self.coord.advance(getattr(self.ds, "pass_epoch", None))
                attempt += 1
                if attempt > self.retry.retries:
                    if not escalated and self.checkpoint is not None:
                        self._escalate(attempt, e)
                        escalated = True
                        attempt = 0
                        continue
                    if self.on_give_up == "skip":
                        self._record("gave_up", "skip", attempt, repr(e))
                        return None
                    self._record("gave_up", "raise", attempt, repr(e))
                    raise PassFailure(
                        f"pass {self._pass_seq} failed after retries"
                        + (" and checkpoint resume" if escalated else "")
                    ) from e
                self.retry.sleep(self.retry.backoff(attempt))
        if self._admit_poisoned and rep is not None:
            # degrade accounting: the pass manifest records what was lost
            out["quarantined_line_fraction"] = float(rep["line_fraction"])
            out["quarantined_bad_lines"] = float(rep["bad_lines"])
            out["quarantined_bad_files"] = float(rep["bad_files"])
        auc = out.get("auc")
        if auc is not None and np.isfinite(auc):
            self._auc_history.append(float(auc))
        if save is not None:
            self._save_checkpoint(save)
        STAT_OBSERVE("supervisor.pass_s", time.monotonic() - pass_t0)
        if self.metrics is not None:
            # pass-boundary series point: counters + per-pass deltas +
            # histogram summaries, labeled so obs_report can build the
            # per-pass table without guessing at boundaries
            self.metrics.snapshot(
                f"pass:{self._pass_seq}",
                extra={
                    k: float(v)
                    for k, v in out.items()
                    if isinstance(v, (int, float)) and np.isfinite(v)
                },
            )
        return out

    def run_day(
        self,
        date: str,
        pass_files: Sequence[Sequence[str]],
        n_batches: Optional[int] = None,
        publish: bool = True,
    ) -> List[Optional[Dict[str, float]]]:
        """One day = base save after the first pass, delta saves after the
        rest (the reference's SaveBase + per-pass need_save_delta cadence).
        ``publish=False`` trains without checkpointing."""
        outs: List[Optional[Dict[str, float]]] = []
        do_save = publish and self.checkpoint is not None
        for p, files in enumerate(pass_files):
            mode = None if not do_save else ("base" if p == 0 else "delta")
            nxt = (
                (date, tuple(pass_files[p + 1]))
                if p + 1 < len(pass_files)
                else None
            )
            outs.append(
                self.run_pass(
                    files, date=date, n_batches=n_batches, save=mode,
                    prefetch=nxt,
                )
            )
            if self.elastic is not None and self.coord is not None:
                # confirmed + published boundary: the one place membership
                # may grow (admit a waiting joiner) or ownership may move
                # planned ranges — either way an atomic epoch flip on a
                # global fingerprint-tagged commit verdict
                try:
                    self._boundary_elastic(do_save)
                except PeerDeadError as e:
                    # a rank died during the boundary round: membership
                    # handling, then the next pass runs on the survivors
                    self._handle_rank_death(e)
            if self.metrics is not None:
                # wall-clock cadence between the per-pass points: on long
                # passes obs_metrics_interval_s paces extra ticks
                self.metrics.maybe_snapshot()
        return outs
