from paddlebox_tpu.train.train_step import TrainState, make_train_step, TrainStepConfig
from paddlebox_tpu.train.sharded_step import (
    init_sharded_train_state,
    kstep_sync_params,
    make_sharded_train_step,
)
from paddlebox_tpu.train.async_dense import AsyncDenseTable
from paddlebox_tpu.train.checkpoint import (
    CheckpointManager,
    DeltaLineageError,
    MembershipEpochError,
    read_watermark,
    validate_watermark,
)
from paddlebox_tpu.data.quarantine import DataPoisonedError
from paddlebox_tpu.train.supervisor import (
    CoordinatedAbort,
    ElasticConfig,
    EpochCoordinator,
    HealthGates,
    PassFailure,
    PassRejected,
    PassSupervisor,
    RetryPolicy,
)
from paddlebox_tpu.train.stream import (
    DirectoryTailer,
    StreamLineageError,
    StreamSupervisor,
)
from paddlebox_tpu.train.trainer import CTRTrainer

__all__ = [
    "TrainState",
    "make_train_step",
    "TrainStepConfig",
    "init_sharded_train_state",
    "kstep_sync_params",
    "make_sharded_train_step",
    "AsyncDenseTable",
    "CTRTrainer",
    "CheckpointManager",
    "CoordinatedAbort",
    "DataPoisonedError",
    "DeltaLineageError",
    "ElasticConfig",
    "MembershipEpochError",
    "read_watermark",
    "validate_watermark",
    "EpochCoordinator",
    "HealthGates",
    "PassFailure",
    "PassRejected",
    "PassSupervisor",
    "RetryPolicy",
    "DirectoryTailer",
    "StreamLineageError",
    "StreamSupervisor",
]
