from paddlebox_tpu.train.train_step import TrainState, make_train_step, TrainStepConfig

__all__ = ["TrainState", "make_train_step", "TrainStepConfig"]
