"""paddlebox_tpu — a TPU-native framework with the capabilities of PaddleBox.

PaddleBox (Baidu's PaddlePaddle fork) trains ultra-large-scale CTR models on a
GPU parameter server: slot-based samples, pass (day/hour) based training, an
HBM/mem/SSD tiered sparse table, online AUC, and base+delta model publishing.

This package re-expresses that vertical slice TPU-first on JAX/XLA/Pallas:

- ``data``      slot sample parsing, columnar ragged batches, pass-scoped
                datasets with preload overlap and global shuffle
                (reference: paddle/fluid/framework/{data_feed,data_set}.*)
- ``table``     the open sparse table: host tiered store + pass working set
                (reference: closed libbox_ps.so behind fleet/box_wrapper.*)
- ``ops``       pull/push sparse, fused seqpool+CVM, cvm, rank_attention,
                batch_fc (reference: paddle/fluid/operators/*)
- ``parallel``  device meshes, sharded-table all-to-all pull/push, dense
                K-step sync (reference: NCCL/MPI collective stack)
- ``metrics``   online AUC / metric registry (reference: BasicAucCalculator)
- ``models``    CTR model zoo: LR, Wide&Deep, DeepFM, DCN, MMoE
- ``train``     BoxWrapper/BoxHelper-parity pass lifecycle + trainers
- ``utils``     fs/hdfs IO, timers, monitor stats

Design note (TPU-first, not a port): keys are remapped host-side to dense
pass-local row indices while batches are packed, so every device-side sparse
op is a static-shape gather/scatter over a mesh-sharded HBM array — no
device hash tables, no dynamic shapes, XLA-friendly end to end.
"""

__version__ = "0.1.0"

from paddlebox_tpu import config  # noqa: F401
from paddlebox_tpu.boxps import BoxWrapper  # noqa: F401  (reference façade)
