"""Role maker + multi-host bootstrap.

Parity targets: ``PaddleCloudRoleMaker`` reads PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / POD_IP / PADDLE_PORT from the scheduler environment
(incubate/fleet/base/role_maker.py:480-690); ``MPISymetricRoleMaker`` gets
the same from mpi4py (:265); Gloo HTTP/HDFS stores provide rendezvous
(gloo_wrapper.h:136-149).

On TPU the rendezvous/collective bootstrap is ``jax.distributed``
(coordinator address + process id + process count), after which every
collective is an XLA op over ICI/DCN — no Gloo/brpc tier. The role maker
normalizes the env dialects (native JAX vars, TPU metadata, or the
reference's PADDLE_* names) into (rank, world, coordinator).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class RoleMaker:
    rank: int  # this process's index (worker_index parity)
    world: int  # number of processes (worker_num parity)
    coordinator: Optional[str] = None  # "host:port" of process 0

    @property
    def is_first_worker(self) -> bool:
        return self.rank == 0

    def worker_index(self) -> int:
        return self.rank

    def worker_num(self) -> int:
        return self.world

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "RoleMaker":
        """Resolve rank/world/coordinator from the first env dialect found:
        JAX native -> reference PADDLE_* -> single-process default.

        Every malformed resolution raises ValueError NAMING the offending
        environment variable — a bad scheduler env must fail at role
        resolution, not minutes later inside socket/rendezvous code."""
        e = os.environ if env is None else env

        def first(*names, default=None):
            """Returns (source_var_name, value) of the first set variable."""
            for n in names:
                if e.get(n) not in (None, ""):
                    return n, e[n]
            return None, default

        def as_int(src, raw, what):
            try:
                return int(raw)
            except (TypeError, ValueError):
                raise ValueError(
                    f"{src}={raw!r} is not a valid integer {what}"
                ) from None

        rank_src, rank_raw = first("JAX_PROCESS_ID", "PADDLE_TRAINER_ID", default="0")
        world_src, world_raw = first(
            "JAX_NUM_PROCESSES", "PADDLE_TRAINERS_NUM", default="1"
        )
        rank = as_int(rank_src or "JAX_PROCESS_ID (default)", rank_raw, "rank")
        world = as_int(
            world_src or "JAX_NUM_PROCESSES (default)", world_raw, "world size"
        )
        if world <= 0:
            raise ValueError(
                f"{world_src or 'JAX_NUM_PROCESSES'}={world_raw!r}: world "
                "size must be >= 1"
            )
        if not (0 <= rank < world):
            raise ValueError(
                f"{rank_src or 'JAX_PROCESS_ID'}={rank_raw!r}: rank {rank} "
                f"out of range for world {world} "
                f"(from {world_src or 'default'})"
            )
        _, coord = first("JAX_COORDINATOR_ADDRESS")
        if coord is None:
            ip, port = e.get("POD_IP"), e.get("PADDLE_PORT")
            if ip and port:
                coord = f"{ip}:{port}"
        if world > 1 and coord is None:
            raise ValueError(
                f"{world_src}={world_raw!r} declares a multi-process role "
                "but no coordinator is set (set JAX_COORDINATOR_ADDRESS or "
                "POD_IP+PADDLE_PORT)"
            )
        return RoleMaker(rank=rank, world=world, coordinator=coord)


_initialized = False


def init_distributed(role: Optional[RoleMaker] = None) -> RoleMaker:
    """Bring up the multi-host runtime (fleet.init parity).

    Single-process roles return immediately — local meshes need no
    rendezvous. Multi-process roles call ``jax.distributed.initialize``,
    the MPI/Gloo-store replacement: after it, ``jax.devices()`` spans all
    hosts and mesh collectives ride ICI/DCN.
    """
    global _initialized
    role = role if role is not None else RoleMaker.from_env()
    if role.world > 1 and not _initialized:
        import jax

        jax.distributed.initialize(
            coordinator_address=role.coordinator,
            num_processes=role.world,
            process_id=role.rank,
        )
        _initialized = True
    return role
