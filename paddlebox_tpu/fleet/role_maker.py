"""Role maker + multi-host bootstrap.

Parity targets: ``PaddleCloudRoleMaker`` reads PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / POD_IP / PADDLE_PORT from the scheduler environment
(incubate/fleet/base/role_maker.py:480-690); ``MPISymetricRoleMaker`` gets
the same from mpi4py (:265); Gloo HTTP/HDFS stores provide rendezvous
(gloo_wrapper.h:136-149).

On TPU the rendezvous/collective bootstrap is ``jax.distributed``
(coordinator address + process id + process count), after which every
collective is an XLA op over ICI/DCN — no Gloo/brpc tier. The role maker
normalizes the env dialects (native JAX vars, TPU metadata, or the
reference's PADDLE_* names) into (rank, world, coordinator).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class RoleMaker:
    rank: int  # this process's index (worker_index parity)
    world: int  # number of processes (worker_num parity)
    coordinator: Optional[str] = None  # "host:port" of process 0

    @property
    def is_first_worker(self) -> bool:
        return self.rank == 0

    def worker_index(self) -> int:
        return self.rank

    def worker_num(self) -> int:
        return self.world

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "RoleMaker":
        """Resolve rank/world/coordinator from the first env dialect found:
        JAX native -> reference PADDLE_* -> single-process default."""
        e = os.environ if env is None else env

        def first(*names, default=None):
            for n in names:
                if e.get(n) not in (None, ""):
                    return e[n]
            return default

        rank = int(first("JAX_PROCESS_ID", "PADDLE_TRAINER_ID", default="0"))
        world = int(first("JAX_NUM_PROCESSES", "PADDLE_TRAINERS_NUM", default="1"))
        coord = first("JAX_COORDINATOR_ADDRESS")
        if coord is None:
            ip, port = e.get("POD_IP"), e.get("PADDLE_PORT")
            if ip and port:
                coord = f"{ip}:{port}"
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} out of range for world {world}")
        if world > 1 and coord is None:
            raise ValueError(
                "multi-process role needs a coordinator (set "
                "JAX_COORDINATOR_ADDRESS or POD_IP+PADDLE_PORT)"
            )
        return RoleMaker(rank=rank, world=world, coordinator=coord)


_initialized = False


def init_distributed(role: Optional[RoleMaker] = None) -> RoleMaker:
    """Bring up the multi-host runtime (fleet.init parity).

    Single-process roles return immediately — local meshes need no
    rendezvous. Multi-process roles call ``jax.distributed.initialize``,
    the MPI/Gloo-store replacement: after it, ``jax.devices()`` spans all
    hosts and mesh collectives ride ICI/DCN.
    """
    global _initialized
    role = role if role is not None else RoleMaker.from_env()
    if role.world > 1 and not _initialized:
        import jax

        jax.distributed.initialize(
            coordinator_address=role.coordinator,
            num_processes=role.world,
            process_id=role.rank,
        )
        _initialized = True
    return role
