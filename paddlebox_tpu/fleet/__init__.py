"""fleet: the distributed-strategy / role / launch tier.

TPU-native parity layer for the reference's two fleet stacks:

- fleet v1 (pslib mode, incubate/fleet/parameter_server/pslib/__init__.py:
  43-761): init/init_worker/stop_worker/save surface over role makers;
- fleet v2 (python/paddle/distributed/fleet): proto-backed
  ``DistributedStrategy`` (distributed_strategy.py:101-829) whose flags pick
  meta-optimizers (a_sync, localsgd, sharding, recompute, amp, pipeline),
  env-driven ``PaddleCloudRoleMaker`` (role_maker.py:480), and the
  multiprocess launcher.

Here the strategy flags translate onto the framework's native mechanisms
(strategy.py), the role maker reads TPU/host env and drives
``jax.distributed`` (role_maker.py), and ZeRO-1 optimizer-state sharding
(sharding_optimizer.py parity) is an exact chunked wrapper over any
elementwise optax optimizer (zero.py).
"""

from paddlebox_tpu.fleet.strategy import DistributedStrategy
from paddlebox_tpu.fleet.role_maker import RoleMaker, init_distributed
from paddlebox_tpu.fleet.zero import Zero1Optimizer

__all__ = [
    "DistributedStrategy",
    "RoleMaker",
    "init_distributed",
    "Zero1Optimizer",
]
