"""ZeRO-1: optimizer-state sharding along the data-parallel axis.

Parity with fleet v2's sharding meta-optimizer (meta_optimizers/
sharding_optimizer.py + sharding/*): dense params stay replicated, but the
optimizer STATE (Adam moments etc.) is partitioned 1/n per device; each
device updates only its parameter shard and an all-gather rebuilds the full
update.

Mechanics: all params ravel into one flat vector, zero-padded to n_dev
equal chunks. Host-side ``init_stacked`` builds the per-chunk inner state
with a leading [n_dev] axis (to be placed dp-sharded); inside shard_map,
``update_local`` takes the (replicated, already psum'd) grads, updates this
device's chunk with the inner optimizer, and ``all_gather``s the chunk
updates back into a full update pytree.

Exactness: for elementwise optimizers (adam/adagrad/sgd/rmsprop — all of
optax's standard transforms) chunked update == full update, so ZeRO-1 here
is bit-compatible with the unsharded trajectory while holding 1/n of the
moment memory per device.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.flatten_util import ravel_pytree


class Zero1Optimizer:
    """Chunked wrapper over an elementwise optax optimizer."""

    def __init__(
        self,
        inner: optax.GradientTransformation,
        axis_name: str = "dp",
        n_dev: int = 1,
    ):
        self.inner = inner
        self.axis_name = axis_name
        self.n_dev = n_dev

    # Deliberately NOT the optax interface: chunk selection needs the mesh
    # axis context, so this optimizer only works inside the sharded step.
    # These guards turn the wrong-path AttributeError into a real message.
    def init(self, params):
        raise RuntimeError(
            "Zero1Optimizer state is mesh-sharded: it runs inside "
            "make_sharded_train_step (init via init_sharded_train_state) "
            "or make_pipeline_train_step with dp_axis (init via "
            "init_pipeline_state). For single-device training use the "
            "inner optimizer."
        )

    def update(self, grads, state, params=None):
        self.init(params)  # same message

    def check_axis(self, axis_name: str, n_axis: int) -> None:
        """Validate this optimizer against the mesh axis it will chunk
        over (one chunk per device along that axis). Single source for the
        checks every consumer (sharded step, pipeline step, state init)
        must make — they would otherwise drift apart."""
        if self.axis_name != axis_name:
            raise ValueError(
                f"Zero1Optimizer chunks over axis {self.axis_name!r}, "
                f"step/state built for axis {axis_name!r}"
            )
        if self.n_dev != n_axis:
            raise ValueError(
                f"Zero1Optimizer built for {self.n_dev} devices, axis "
                f"{axis_name!r} has {n_axis}"
            )

    def _chunks(self, tree: Any) -> Tuple[jnp.ndarray, Any, int]:
        """ravel -> pad -> [n_dev, c]; returns (chunks, unravel, true_len)."""
        flat, unravel = ravel_pytree(tree)
        n = flat.shape[0]
        c = -(-n // self.n_dev)
        padded = jnp.pad(flat, (0, c * self.n_dev - n))
        return padded.reshape(self.n_dev, c), unravel, n

    # ---- host side (outside shard_map) ----------------------------------

    def init_stacked(self, params: Any) -> Any:
        """Inner state per chunk, leaves stacked [n_dev, ...] — place this
        dp-sharded so device i physically holds only chunk i's moments."""
        chunks, _, _ = self._chunks(params)
        return jax.vmap(self.inner.init)(chunks)

    # ---- device side (inside shard_map over axis_name) ------------------

    def update_local(
        self, grads: Any, opt_state_local: Any, params: Any
    ) -> Tuple[Any, Any]:
        """(updates pytree, new local state). ``grads`` must already be the
        global (psum'd/pmean'd) grads — replicated across the axis — so
        every device chunks the same vector."""
        idx = lax.axis_index(self.axis_name)
        gchunks, unravel, n = self._chunks(grads)
        pchunks, _, _ = self._chunks(params)
        my_g = gchunks[idx]
        my_p = pchunks[idx]
        upd_chunk, new_state = self.inner.update(my_g, opt_state_local, my_p)
        all_upd = lax.all_gather(upd_chunk, self.axis_name)  # [n_dev, c]
        return unravel(all_upd.reshape(-1)[:n]), new_state

    def state_memory_fraction(self) -> float:
        return 1.0 / self.n_dev
