"""DistributedStrategy: one config object that picks the execution strategy.

Parity with fleet v2's proto-backed strategy (distributed_strategy.py:
101-829). Each reference flag maps onto this framework's native mechanism —
the translation table is the point of the class:

| reference flag            | here                                         |
|---------------------------|----------------------------------------------|
| a_sync                    | dense_sync_mode="async" (host AsyncDenseTable)|
| a_sync_configs.k_steps>0  | dense_sync_mode="kstep" + param_sync_step    |
| localsgd(+k_steps)        | dense_sync_mode="kstep" + param_sync_step    |
| sharding (ZeRO)           | Zero1Optimizer wrap of the dense optimizer   |
| recompute                 | jax.checkpoint around model apply            |
| amp                       | bf16 compute dtype for the dense model       |
| pipeline(+micro_batch)    | PipelineSpec over a 'pp' mesh axis           |
| gradient_merge(+k_steps)  | optax.MultiSteps accumulation                |

``apply()`` folds the flags into a TrainStepConfig + optax optimizer, so
``fleet``-style user code stays declarative while the step builders remain
explicit underneath.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple

import jax
import optax


@dataclass
class DistributedStrategy:
    # async PS (a_sync, distributed_strategy.py:239-320)
    a_sync: bool = False
    a_sync_configs: Dict[str, Any] = field(default_factory=dict)  # {"k_steps": int}
    # LocalSGD (distributed_strategy.py:778-829)
    localsgd: bool = False
    localsgd_configs: Dict[str, Any] = field(default_factory=lambda: {"k_steps": 16})
    # ZeRO-style sharding (distributed_strategy.py:658-708)
    sharding: bool = False
    sharding_configs: Dict[str, Any] = field(default_factory=dict)
    # recompute / amp (distributed_strategy.py:322-652)
    recompute: bool = False
    amp: bool = False
    # pipeline (distributed_strategy.py:714-734)
    pipeline: bool = False
    pipeline_configs: Dict[str, Any] = field(default_factory=lambda: {"micro_batch": 4})
    # gradient merge (accumulation)
    gradient_merge: bool = False
    gradient_merge_configs: Dict[str, Any] = field(default_factory=lambda: {"k_steps": 4})

    def __post_init__(self):
        if self.a_sync and self.localsgd:
            raise ValueError("a_sync and localsgd are mutually exclusive")
        if self.pipeline and (self.a_sync or self.localsgd):
            raise ValueError(
                "pipeline composes with neither a_sync nor localsgd here: "
                "pipeline stages own their params — there is no DP dense "
                "sync to reconfigure"
            )
        if self.pipeline and self.sharding and self.pipeline_dp_degree < 2:
            raise ValueError(
                "pipeline + sharding needs a dp axis to chunk over: set "
                "pipeline_configs['dp_degree'] > 1 (pp x dp mesh; pass a "
                "Zero1Optimizer over the dp axis to "
                "make_pipeline_train_step)"
            )

    # ---- translation ----------------------------------------------------

    @property
    def dense_sync_mode(self) -> str:
        """The TrainStepConfig dense mode these flags select. Mirrors the
        reference's a_sync_configs semantics: a_sync with k_steps == 0 is
        fully async, k_steps > 0 is 'geo'/k-step sync (distributed_strategy
        .py:274-316); localsgd is k-step by definition."""
        if self.a_sync:
            return "kstep" if self.a_sync_configs.get("k_steps", 0) > 0 else "async"
        if self.localsgd:
            return "kstep"
        return "step"

    @property
    def k_steps(self) -> int:
        if self.a_sync:
            return max(1, self.a_sync_configs.get("k_steps", 0))
        return max(1, self.localsgd_configs.get("k_steps", 16))

    def apply(
        self,
        cfg: "TrainStepConfig",
        dense_opt: optax.GradientTransformation,
        model_apply=None,
        n_dev: int = 1,
        axis_name: str = "dp",
    ) -> Tuple["TrainStepConfig", optax.GradientTransformation, Any]:
        """Fold the strategy into (cfg, optimizer, model_apply).

        ``pipeline`` does not fold into a TrainStepConfig — pipeline
        training is a different step builder; take ``pipeline_spec()`` to
        ``make_pipeline_train_step`` instead.
        """
        if self.pipeline:
            raise ValueError(
                "pipeline=True selects a different step builder: use "
                "strategy.pipeline_spec() with "
                "paddlebox_tpu.parallel.make_pipeline_train_step"
            )
        cfg = replace(
            cfg,
            dense_sync_mode=self.dense_sync_mode,
            param_sync_step=self.k_steps,
        )
        if self.gradient_merge:
            dense_opt = optax.MultiSteps(
                dense_opt, self.gradient_merge_configs.get("k_steps", 4)
            )
        if self.sharding:
            from paddlebox_tpu.fleet.zero import Zero1Optimizer

            dense_opt = Zero1Optimizer(dense_opt, axis_name=axis_name, n_dev=n_dev)
        if model_apply is not None and self.recompute:
            model_apply = jax.checkpoint(model_apply)
        if model_apply is not None and self.amp:
            inner = model_apply

            def bf16_apply(params, *args, **kw):
                cast = lambda t: jax.tree.map(
                    lambda x: x.astype("bfloat16")
                    if hasattr(x, "dtype") and x.dtype == "float32"
                    else x,
                    t,
                )
                out = inner(cast(params), *[cast(a) for a in args], **kw)
                return jax.tree.map(lambda x: x.astype("float32"), out)

            model_apply = bf16_apply
        return cfg, dense_opt, model_apply

    def pipeline_spec(self, axis_name: str = "pp"):
        """PipelineSpec from pipeline_configs, for make_pipeline_train_step.

        ``pipeline_configs['dp_degree'] > 1`` selects the pipeline x data
        composition: build the mesh with ``make_mesh_2d(n_pp, dp_degree)``
        and pass ``dp_axis='dp'`` to make_pipeline_train_step (the
        reference layers PipelineTrainer sections under fleet DP ranks the
        same way)."""
        from paddlebox_tpu.parallel.pipeline import PipelineSpec

        if not self.pipeline:
            raise ValueError("strategy.pipeline is False")
        return PipelineSpec(
            n_micro=self.pipeline_configs.get("micro_batch", 4),
            axis_name=axis_name,
        )

    @property
    def pipeline_dp_degree(self) -> int:
        """Data-parallel replicas per pipeline stage (1 = pure pipeline)."""
        return int(self.pipeline_configs.get("dp_degree", 1))
