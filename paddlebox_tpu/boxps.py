"""BoxWrapper façade: the reference's singleton surface in one object.

For users coming from the reference, ``core.BoxWrapper`` is the center of
the world (box_wrapper.h:362-774, pybind box_helper_py.cc:40-140): it owns
the sparse model, the pass/phase machinery, the metric registry, and model
publishing. This framework deliberately decomposes those into table/,
metrics/, data/, and train/ — this façade packages them back behind the
familiar names so migration is mechanical:

    box = BoxWrapper(embedx_dim=16)                    # SetInstance parity
    ds = box.make_dataset(schema, batch_size=4096)     # BoxPSDataset
    box.init_metric("join_auc", phase=1)               # init_metric parity
    ... pass loop via ds.begin_pass()/trainer/ds.end_pass() ...
    box.save_base("ckpt", date)                        # SaveBase parity
    box.get_metric_msg("join_auc")

Everything here delegates; no behavior lives in the façade.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence

from paddlebox_tpu.metrics.registry import MetricRegistry
from paddlebox_tpu.table.optimizers import SparseOptimizerConfig
from paddlebox_tpu.table.sparse_table import HostSparseTable
from paddlebox_tpu.table.value_layout import FeatureType, ValueLayout
from paddlebox_tpu.train.checkpoint import CheckpointManager


class BoxWrapper:
    """One process's sparse model + phases + metrics + publishing."""

    def __init__(
        self,
        embedx_dim: int = 8,
        expand_embed_dim: int = 0,
        feature_type: FeatureType = FeatureType.PLAIN,
        pull_embedx_scale: float = 1.0,
        sparse_opt: Optional[SparseOptimizerConfig] = None,
        n_host_shards: int = 64,
        seed: int = 0,
    ):
        self.layout = ValueLayout(
            embedx_dim=embedx_dim,
            expand_embed_dim=expand_embed_dim,
            feature_type=feature_type,
        )
        self.pull_embedx_scale = pull_embedx_scale
        self.sparse_opt = sparse_opt or SparseOptimizerConfig()
        self.table = HostSparseTable(
            self.layout, self.sparse_opt, n_shards=n_host_shards, seed=seed
        )
        self.metrics = MetricRegistry()
        # two-phase join/update machinery (box_wrapper.h:620-622)
        self.phase = 1
        self.phase_num = 2
        self.test_mode = False
        self._ckpt: Optional[CheckpointManager] = None

    # ---- phase machinery -------------------------------------------------

    def flip_phase(self) -> int:
        """FlipPhase parity: 1 (join) <-> 0 (update)."""
        self.phase ^= 1
        return self.phase

    def set_test_mode(self, on: bool = True) -> None:
        """SetTestMode parity (box_wrapper.cc:623): a CTRTrainer constructed
        with ``box=this`` runs its next train_pass as forward+metrics only —
        no sparse push, no dense update (infer_from_dataset parity,
        executor.py:1520)."""
        self.test_mode = on

    # ---- dataset ---------------------------------------------------------

    def make_dataset(self, schema, batch_size: int, **kw) -> "BoxPSDataset":
        """BoxPSDataset bound to this wrapper's table (DatasetFactory +
        BoxHelper binding parity)."""
        from paddlebox_tpu.data.dataset import BoxPSDataset

        return BoxPSDataset(schema, self.table, batch_size=batch_size, **kw)

    # ---- metrics (init_metric/get_metric_msg parity, box_helper_py.cc:87-97)

    def init_metric(self, name: str, **kw) -> None:
        self.metrics.init_metric(name=name, **kw)

    def get_metric_msg(self, name: str) -> str:
        return self.metrics.get_metric_msg(name)

    def get_metric(self, name: str) -> Dict[str, float]:
        return self.metrics.get_metric(name)

    # ---- model publishing (SaveBase/SaveDelta/load parity) ---------------

    def checkpoint_manager(self, root: str) -> CheckpointManager:
        if self._ckpt is None or self._ckpt.root != root:
            self._ckpt = CheckpointManager(root)
        return self._ckpt

    def save_base(self, root: str, date: str, trainer=None) -> str:
        return self.checkpoint_manager(root).save_base(date, self.table, trainer)

    def save_delta(self, root: str, date: str, trainer=None) -> str:
        return self.checkpoint_manager(root).save_delta(date, self.table, trainer)

    def load_model(self, root: str, trainer=None):
        """Day-level resume (InitializeGPUAndLoadModel + LoadSSD2Mem parity):
        newest base + its deltas into the table, dense into the trainer."""
        return self.checkpoint_manager(root).resume(self.table, trainer)

    def save_cache_model(self, root: str, date: str, cache_rate: float = 0.1) -> int:
        """Hot-key serving cache (save_cache_model parity, pslib
        __init__.py:386-425): derive the show threshold admitting
        ``cache_rate`` of keys, write them under <date>/cache/, return the
        feasign count.

        Call between passes (the reference brackets the same two-phase
        protocol in worker barriers): a push landing between the threshold
        scan and the save shifts the cut."""
        thr = self.table.cache_threshold(cache_rate)
        return self.table.save_cache(os.path.join(root, date, "cache"), thr)

    def save_model_with_whitelist(self, root: str, date: str, whitelist) -> int:
        """Whitelisted-keys snapshot (save_model_with_whitelist parity,
        pslib __init__.py:351-384) under <date>/whitelist/."""
        return self.table.save_with_whitelist(
            os.path.join(root, date, "whitelist"), whitelist
        )
