"""Log2-bucketed streaming histograms — the distribution half of the
telemetry plane.

Prometheus-style fixed-boundary histograms force every subsystem to guess
its own bucket layout up front; HDR-style log buckets don't. Each positive
observation lands in the bucket ``[2**(e-1), 2**e)`` chosen by
``math.frexp`` — ~1 bit of relative error, any dynamic range, O(1)
memory per decade — while exact ``count``/``sum``/``min``/``max`` ride
alongside so means and extremes are never estimates. Quantiles are
estimated by rank interpolation inside the owning bucket and clamped to
the exact ``[min, max]``, which keeps them monotone in ``q`` and strictly
positive whenever every observation was.

The class is dependency-free on purpose: ``utils/monitor.py`` imports it
for ``STAT_OBSERVE`` and everything else in the package imports monitor,
so anything this module pulled in would become a package-wide import
cycle.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

# observations at or below zero (timer underflow, a zero-length batch)
# are real data — they get a dedicated bucket keyed by this sentinel
# exponent, below every frexp exponent of a positive float.
_NONPOS_EXP = -5000


def _bucket_exp(value: float) -> int:
    """Exponent ``e`` such that value is in ``[2**(e-1), 2**e)``."""
    if value <= 0.0:
        return _NONPOS_EXP
    # frexp: value = m * 2**e with 0.5 <= m < 1  =>  2**(e-1) <= value < 2**e
    return math.frexp(value)[1]


def _bucket_bounds(exp: int) -> Tuple[float, float]:
    if exp == _NONPOS_EXP:
        return (0.0, 0.0)
    return (math.ldexp(1.0, exp - 1), math.ldexp(1.0, exp))


class Histogram:
    """Thread-safe log2 histogram with exact count/sum/min/max."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._buckets: Dict[int, int] = {}  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._min = math.inf  # guarded-by: _lock
        self._max = -math.inf  # guarded-by: _lock

    # -- ingest ----------------------------------------------------------
    def observe(self, value: float) -> None:
        v = float(value)
        exp = _bucket_exp(v)
        with self._lock:
            self._buckets[exp] = self._buckets.get(exp, 0) + 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    def observe_many(self, values: Iterable[float]) -> None:
        for v in values:
            self.observe(v)

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram in (cross-rank / cross-window union)."""
        snap = other._snapshot_locked()
        with self._lock:
            for exp, n in snap["buckets"].items():
                self._buckets[exp] = self._buckets.get(exp, 0) + n
            self._count += snap["count"]
            self._sum += snap["sum"]
            self._min = min(self._min, snap["min"])
            self._max = max(self._max, snap["max"])

    # -- read ------------------------------------------------------------
    def _snapshot_locked(self) -> Dict:
        with self._lock:
            return {
                "buckets": dict(self._buckets),
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
            }

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def min(self) -> float:
        """Exact minimum observed (``inf`` when empty)."""
        with self._lock:
            return self._min

    @property
    def max(self) -> float:
        with self._lock:
            return self._max

    def quantile(self, q: float) -> float:
        return self.quantiles([q])[0]

    def quantiles(self, qs: Iterable[float]) -> List[float]:
        """Estimate several quantiles from ONE consistent snapshot.

        Rank interpolation inside the owning log2 bucket, clamped to the
        exact extremes: relative error is bounded by the bucket width
        (~2x worst case, far less in practice because the exact min/max
        pin the tails). Returns ``nan`` per quantile when empty.
        """
        snap = self._snapshot_locked()
        out: List[float] = []
        if snap["count"] == 0:
            return [math.nan for _ in qs]
        ordered = sorted(snap["buckets"].items())
        total = snap["count"]
        for q in qs:
            qc = min(max(float(q), 0.0), 1.0)
            # rank in [0, total-1], numpy 'linear' convention
            rank = qc * (total - 1)
            est = snap["max"]
            cum = 0
            for exp, n in ordered:
                if rank < cum + n:
                    lo, hi = _bucket_bounds(exp)
                    frac = (rank - cum + 0.5) / n  # midpoint-of-rank
                    est = lo + (hi - lo) * frac
                    break
                cum += n
            out.append(min(max(est, snap["min"]), snap["max"]))
        return out

    def summary(self, qs: Iterable[float] = (0.5, 0.9, 0.99)) -> Dict:
        """One JSON-ready dict: exact aggregates + estimated quantiles."""
        snap = self._snapshot_locked()
        qlist = list(qs)
        vals = self.quantiles(qlist) if snap["count"] else []
        s = {
            "count": snap["count"],
            "sum": snap["sum"],
            "min": snap["min"] if snap["count"] else None,
            "max": snap["max"] if snap["count"] else None,
            "mean": (snap["sum"] / snap["count"]) if snap["count"] else None,
        }
        for q, v in zip(qlist, vals):
            s[f"p{_q_label(q)}"] = v
        return s

    # -- (de)serialization ----------------------------------------------
    def to_dict(self) -> Dict:
        snap = self._snapshot_locked()
        return {
            # JSON object keys must be strings; exponents round-trip via str
            "buckets": {str(e): n for e, n in snap["buckets"].items()},
            "count": snap["count"],
            "sum": snap["sum"],
            "min": None if snap["count"] == 0 else snap["min"],
            "max": None if snap["count"] == 0 else snap["max"],
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "Histogram":
        h = cls()
        h._buckets = {int(e): int(n) for e, n in d.get("buckets", {}).items()}
        h._count = int(d.get("count", 0))
        h._sum = float(d.get("sum", 0.0))
        h._min = math.inf if d.get("min") is None else float(d["min"])
        h._max = -math.inf if d.get("max") is None else float(d["max"])
        return h


def _q_label(q: float) -> str:
    """0.5 -> '50', 0.99 -> '99', 0.999 -> '99.9'."""
    pct = q * 100.0
    if abs(pct - round(pct)) < 1e-9:
        return str(int(round(pct)))
    return ("%g" % pct)


def merge_all(hists: Iterable[Optional[Histogram]]) -> Histogram:
    """Union of histograms (skipping None), e.g. across ranks."""
    out = Histogram()
    for h in hists:
        if h is not None:
            out.merge(h)
    return out
