"""paddlebox_tpu.obs — the unified telemetry plane.

Four pieces, one registry:

- ``histogram``      log2-bucketed distributions behind ``STAT_OBSERVE``
- ``metrics_writer`` rank-tagged JSONL series of registry snapshots
- ``trace_context``  (trace_id, span_id) propagation across PBTX frames
- ``flight_recorder`` always-on ring of recent spans/stats/incidents,
                      dumped as ``incident-<ts>.json`` on fatal errors

Exports are lazy (PEP 562): ``utils/monitor.py`` imports
``obs.histogram`` at import time, and ``metrics_writer``/
``flight_recorder`` import monitor back — eager re-exports here would
close that loop into an ImportError.
"""

from __future__ import annotations

_LAZY = {
    "Histogram": ("paddlebox_tpu.obs.histogram", "Histogram"),
    "merge_all": ("paddlebox_tpu.obs.histogram", "merge_all"),
    "MetricsWriter": ("paddlebox_tpu.obs.metrics_writer", "MetricsWriter"),
    "read_series": ("paddlebox_tpu.obs.metrics_writer", "read_series"),
    "TraceContext": ("paddlebox_tpu.obs.trace_context", "TraceContext"),
    "trace_span": ("paddlebox_tpu.obs.trace_context", "trace_span"),
    "current_trace": ("paddlebox_tpu.obs.trace_context", "current_trace"),
    "FlightRecorder": ("paddlebox_tpu.obs.flight_recorder", "FlightRecorder"),
    "FLIGHT_RECORDER": (
        "paddlebox_tpu.obs.flight_recorder", "FLIGHT_RECORDER"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod_name), attr)
