"""Always-on incident flight recorder.

A fixed-size in-memory ring of the most recent profiler spans, instants,
and incident notes — fed unconditionally (tracing enabled or not) by
``utils/trace.py`` — plus a one-call ``dump()`` that publishes an atomic
``incident-<ts>.json`` bundle (recent spans + incidents + a full stat and
histogram snapshot) when something fatal happens: DataPoisonedError,
PeerDeadError, CoordinatedAbort, a wedged backend init. Postmortems no
longer depend on having had tracing enabled in advance: the last N spans
before the death are always there.

The ring is deliberately tiny (flag ``obs_flight_spans``) and lock-cheap;
the expensive parts (stat snapshot, JSON encode, fsync) only run at dump
time, i.e. when the process is already dying or aborting a pass.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from paddlebox_tpu import config
from paddlebox_tpu.utils.monitor import STAT_ADD, all_histograms, all_stats

config.define_flag(
    "obs_flight_spans", 256,
    "flight-recorder ring capacity: how many recent spans survive into "
    "an incident bundle",
)
config.define_flag(
    "obs_incident_dir", "",
    "directory for incident-<ts>.json flight-recorder bundles; empty "
    "disables dumping (the in-memory ring still records)",
)


class FlightRecorder:
    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._capacity = capacity  # None -> flag, resolved lazily
        self._spans: Optional[Deque[Dict]] = None  # guarded-by: _lock
        self._incidents: Deque[Dict] = deque(maxlen=64)  # guarded-by: _lock
        self._rank = 0  # guarded-by: _lock

    def set_rank(self, rank: int) -> None:
        with self._lock:
            self._rank = int(rank)

    # -- feed (called from utils/trace.py on every span/instant) ---------
    def note_span(self, name: str, category: str, ts_us: float,
                  dur_us: float, args: Optional[Dict] = None) -> None:
        rec = {"name": name, "cat": category, "ts": ts_us, "dur": dur_us,
               "thread": threading.current_thread().name}
        if args:
            rec["args"] = args
        with self._lock:
            if self._spans is None:  # lazy: capacity flag resolved on first use
                cap = self._capacity
                if cap is None:
                    cap = int(config.get_flag("obs_flight_spans"))
                self._spans = deque(maxlen=max(1, cap))
            self._spans.append(rec)

    def note_incident(self, kind: str, args: Optional[Dict] = None,
                      category: str = "incident") -> None:
        rec = {"kind": kind, "cat": category, "wall_time": time.time(),
               "args": args or {}}
        with self._lock:
            self._incidents.append(rec)

    # -- read / dump ------------------------------------------------------
    def snapshot(self) -> Dict:
        """The bundle content, without writing anything."""
        with self._lock:
            spans = list(self._spans) if self._spans is not None else []
            incidents = list(self._incidents)
            rank = self._rank
        return {
            "rank": rank,
            "wall_time": time.time(),
            "spans": spans,
            "incidents": incidents,
            "stats": all_stats(),
            "histograms": {
                name: h.to_dict() for name, h in all_histograms().items()
            },
        }

    def dump(self, reason: str, detail: str = "",
             dir_path: Optional[str] = None) -> Optional[str]:
        """Write ``incident-<ts>.json`` atomically; returns the path, or
        None when no dump directory is configured. Never raises: a dump
        runs inside fatal-error handling, and masking the original
        PeerDeadError/DataPoisonedError with an IO error would be worse
        than losing the bundle."""
        out_dir = dir_path if dir_path is not None else str(
            config.get_flag("obs_incident_dir"))
        if not out_dir:
            return None
        bundle = self.snapshot()
        bundle["reason"] = reason
        bundle["detail"] = detail
        path = os.path.join(out_dir, f"incident-{time.time_ns()}.json")
        try:
            from paddlebox_tpu.utils.fs import atomic_write

            os.makedirs(out_dir, exist_ok=True)
            with atomic_write(path) as f:
                json.dump(bundle, f)
        except OSError:
            # counted, not raised: see docstring
            STAT_ADD("obs.incident_dump_errors")
            return None
        STAT_ADD("obs.incident_dumps")
        return path

    def reset(self) -> None:
        """Clear the rings and re-resolve capacity from the flag."""
        with self._lock:
            self._spans = None
            self._incidents.clear()


# process-global recorder, fed by the global PROFILER
FLIGHT_RECORDER = FlightRecorder()


def recent_incidents() -> List[Dict]:
    return FLIGHT_RECORDER.snapshot()["incidents"]
