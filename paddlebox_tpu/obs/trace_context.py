"""Trace-context propagation: (trace_id, span_id) per logical operation.

Dapper-style correlation ids for the host plane. A rank opens a trace
around a logical operation (a shuffle round, a coordinated verdict, a
delta publish); every profiler span recorded inside picks up the ids as
chrome-trace ``args``, and the transport stamps them onto outgoing PBTX
frames as an optional header extension so the RECEIVING rank's delivery
events carry the same trace_id. ``tools/obs_report.py --merge-traces``
then lines the ranks up by trace_id in one fused timeline.

Context is per-thread (``threading.local``): the feed pipeline's packer
threads and the transport reader each see their own current trace, which
is exactly the scoping a span id means. Ids are random (``os.urandom``),
128-bit trace / 64-bit span, hex-encoded in args and fixed-width binary
on the wire (``encode_ext``/``decode_ext``; see parallel/transport.py for
the frame-level gating).

Stdlib-only on purpose — utils/trace.py imports this module at import
time, and nearly everything imports utils.
"""

from __future__ import annotations

import os
import struct
import threading
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

# wire form of one context: 16B trace_id + 8B span_id, big-endian-ish raw
# bytes (opaque ids — byte order only matters for hex round-trip).
EXT_STRUCT = struct.Struct("<16s8s")
EXT_LEN = EXT_STRUCT.size  # 24


class TraceContext:
    """Immutable (trace_id, span_id) pair. Ids are raw bytes."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: bytes, span_id: bytes) -> None:
        self.trace_id = trace_id
        self.span_id = span_id

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(os.urandom(16), os.urandom(8))

    def child(self) -> "TraceContext":
        """Same trace, fresh span — a step inside the operation."""
        return TraceContext(self.trace_id, os.urandom(8))

    @property
    def trace_id_hex(self) -> str:
        return self.trace_id.hex()

    @property
    def span_id_hex(self) -> str:
        return self.span_id.hex()

    def as_args(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id_hex, "span_id": self.span_id_hex}

    def encode_ext(self) -> bytes:
        return EXT_STRUCT.pack(self.trace_id, self.span_id)


def decode_ext(raw: bytes) -> "TraceContext":
    trace_id, span_id = EXT_STRUCT.unpack(raw)
    return TraceContext(trace_id, span_id)


_tls = threading.local()


def current_trace() -> Optional[TraceContext]:
    """The thread's active context, or None outside any trace_span."""
    return getattr(_tls, "ctx", None)


@contextmanager
def trace_span(name: str = "", ctx: Optional[TraceContext] = None,
               ) -> Iterator[TraceContext]:
    """Activate a context for the with-block.

    No explicit ``ctx``: continue the current trace with a child span
    (or start a brand-new trace at the root). With ``ctx`` (e.g. decoded
    off an incoming frame): adopt the remote trace so local spans
    correlate cross-rank. ``name`` is documentation only — the profiler
    spans recorded inside carry the actual labels.
    """
    prev = current_trace()
    if ctx is None:
        ctx = prev.child() if prev is not None else TraceContext.new()
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev
