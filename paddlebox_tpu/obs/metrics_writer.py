"""Metric series: rank-tagged JSONL snapshots of the stat registry.

Every snapshot is one JSON line — wall time, rank, a label (``pass:<n>``
at pass boundaries, ``tick`` on the wall-clock cadence), the full counter
registry, per-window DELTAS for every numeric counter (what happened
since the previous snapshot, not just the monotone absolute), and a
summary of every histogram. ``tools/obs_report.py`` renders the series
into per-pass tables and SLO verdicts; ``read_series`` is the parsing
half it uses.

Durability model: lines are appended with flush (a torn final line after
a crash is skipped — and counted — by ``read_series``); rotation renames
the live file to ``metrics-<rank>.<seq>.jsonl`` via ``os.replace``, the
same atomic publish primitive as ``utils/fs.atomic_write``, so a reader
never observes a half-rotated file.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from paddlebox_tpu import config
from paddlebox_tpu.utils.monitor import STAT_ADD, all_histograms, all_stats

config.define_flag(
    "obs_metrics_interval_s", 30.0,
    "wall-clock cadence for metric-series snapshots between pass "
    "boundaries (maybe_snapshot); <= 0 disables the cadence",
)
config.define_flag(
    "obs_metrics_rotate_bytes", 8 << 20,
    "rotate metrics-<rank>.jsonl once it would exceed this many bytes",
)

_ROTATED_RE = re.compile(r"metrics-(\d+)\.(\d+)\.jsonl$")


class MetricsWriter:
    """Appends registry snapshots to ``<out_dir>/metrics-<rank>.jsonl``."""

    def __init__(
        self,
        out_dir: str,
        rank: int = 0,
        interval_s: Optional[float] = None,
        rotate_bytes: Optional[int] = None,
    ) -> None:
        self.out_dir = out_dir
        self.rank = int(rank)
        self._interval_s = interval_s
        self._rotate_bytes = rotate_bytes
        self._lock = threading.Lock()
        self._prev: Dict[str, Any] = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._rotations = 0  # synchronized-by: _lock (held by _rotate_locked callers)
        self._last_write = 0.0  # guarded-by: _lock
        os.makedirs(out_dir, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.out_dir, f"metrics-{self.rank}.jsonl")

    def _cfg_interval(self) -> float:
        if self._interval_s is not None:
            return float(self._interval_s)
        return float(config.get_flag("obs_metrics_interval_s"))

    def _cfg_rotate(self) -> int:
        if self._rotate_bytes is not None:
            return int(self._rotate_bytes)
        return int(config.get_flag("obs_metrics_rotate_bytes"))

    def snapshot(self, label: str,
                 extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Write one series record now; returns the record."""
        counters = all_stats()
        hists = {
            name: h.summary((0.5, 0.9, 0.99))
            for name, h in all_histograms().items()
        }
        with self._lock:
            deltas = {
                k: v - self._prev.get(k, 0)
                for k, v in counters.items()
                if isinstance(v, (int, float))
            }
            self._seq += 1
            record = {
                "t": time.time(),
                "rank": self.rank,
                "seq": self._seq,
                "label": label,
                "counters": counters,
                "deltas": deltas,
                "histograms": hists,
            }
            if extra:
                record["extra"] = extra
            self._prev = counters
            line = json.dumps(record) + "\n"
            self._rotate_locked(len(line))
            # append-only local series: a torn tail line after a crash is
            # tolerated (read_series skips and counts it), and rotation
            # publishes finished segments atomically via os.replace
            # pbox-lint: disable=IO004
            with open(self.path, "a") as f:
                f.write(line)
                f.flush()
            self._last_write = time.monotonic()
        STAT_ADD("obs.metrics_snapshots")
        return record

    def maybe_snapshot(self, label: str = "tick") -> Optional[Dict[str, Any]]:
        """Snapshot iff the wall-clock cadence elapsed since the last
        write (any label). Cheap to call from a training loop."""
        interval = self._cfg_interval()
        if interval <= 0:
            return None
        with self._lock:
            due = time.monotonic() - self._last_write >= interval
        if not due:
            return None
        return self.snapshot(label)

    def _rotate_locked(self, incoming: int) -> None:
        limit = self._cfg_rotate()
        try:
            size = os.path.getsize(self.path)
        except FileNotFoundError:
            return  # no live file yet -> nothing to rotate
        if size == 0 or size + incoming <= limit:
            return
        self._rotations += 1
        rotated = os.path.join(
            self.out_dir, f"metrics-{self.rank}.{self._rotations}.jsonl"
        )
        os.replace(self.path, rotated)
        STAT_ADD("obs.metrics_rotations")

    @property
    def rotations(self) -> int:
        with self._lock:
            return self._rotations


def series_files(out_dir: str, rank: Optional[int] = None) -> List[str]:
    """All series segments in read order: rotated (by segment number)
    then live, grouped per rank."""
    pat = f"metrics-{rank}" if rank is not None else "metrics-*"
    paths = glob.glob(os.path.join(out_dir, pat + ".jsonl")) + glob.glob(
        os.path.join(out_dir, pat + ".*.jsonl")
    )

    def key(p: str):
        m = _ROTATED_RE.search(p)
        if m:
            return (int(m.group(1)), 0, int(m.group(2)))
        base = os.path.basename(p)
        r = base[len("metrics-"):-len(".jsonl")]
        return (int(r) if r.isdigit() else 1 << 30, 1, 0)

    return sorted(set(paths), key=key)


def series_ranks(out_dir: str) -> List[int]:
    """Distinct ranks with any series segment (live or rotated)."""
    ranks = set()
    for p in series_files(out_dir):
        m = _ROTATED_RE.search(p)
        if m:
            ranks.add(int(m.group(1)))
            continue
        r = os.path.basename(p)[len("metrics-"):-len(".jsonl")]
        if r.isdigit():
            ranks.add(int(r))
    return sorted(ranks)


def read_series(out_dir: str, rank: Optional[int] = None,
                ) -> Iterator[Dict[str, Any]]:
    """Parse every record back, across rotations, skipping (and counting
    in ``obs.metrics_bad_lines``) torn or malformed lines."""
    for path in series_files(out_dir, rank):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    STAT_ADD("obs.metrics_bad_lines")
