"""DeepFM — the flagship benchmark model (BASELINE.md config 3).

Consumes pooled slot records [B, S, F] with F = cvm_offset + embedx_dim:
- first order: the embed_w column summed over slots (the pulled LR weight)
- FM second order over the embedx block: 0.5 * ((Σ_s v)² − Σ_s v²)
- deep tower: MLP over [flattened slot feats ; dense floats]

All three are batched matmul/reduction shapes that map straight onto the
MXU; no per-slot small ops.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import linear_apply, linear_init, mlp_apply, mlp_init


class DeepFM:
    def __init__(
        self,
        num_slots: int,
        feat_width: int,
        embedx_dim: int,
        dense_dim: int = 0,
        hidden: Sequence[int] = (512, 256, 128),
        embed_w_col: int = 2,
    ):
        self.num_slots = num_slots
        self.feat_width = feat_width
        self.embedx_dim = embedx_dim
        self.dense_dim = dense_dim
        self.hidden = tuple(hidden)
        self.embed_w_col = embed_w_col

    def init(self, rng):
        k_mlp, k_out, k_dense = jax.random.split(rng, 3)
        in_dim = self.num_slots * self.feat_width + self.dense_dim
        mlp = mlp_init(k_mlp, in_dim, self.hidden)
        params = {
            "mlp": mlp,
            "out": linear_init(k_out, self.hidden[-1], 1),
            "b": jnp.zeros(()),
        }
        if self.dense_dim:
            params["dense_lin"] = linear_init(k_dense, self.dense_dim, 1)
        return params

    def apply(self, params, slot_feats, dense=None):
        B = slot_feats.shape[0]
        co = self.feat_width - self.embedx_dim
        first = jnp.sum(slot_feats[:, :, self.embed_w_col], axis=1)  # [B]

        v = slot_feats[:, :, co:]  # [B, S, D] embedx block
        sum_v = jnp.sum(v, axis=1)
        fm = 0.5 * jnp.sum(sum_v * sum_v - jnp.sum(v * v, axis=1), axis=1)  # [B]

        deep_in = slot_feats.reshape(B, -1)
        if self.dense_dim and dense is not None:
            deep_in = jnp.concatenate([deep_in, dense], axis=1)
        h = mlp_apply(params["mlp"], deep_in, final_activation=True)
        deep = linear_apply(params["out"], h)[:, 0]

        logit = params["b"] + first + fm + deep
        if self.dense_dim and dense is not None:
            logit = logit + linear_apply(params["dense_lin"], dense)[:, 0]
        return logit
