"""Join-phase rank model: a base CTR tower + rank_attention over the pv
rank matrix.

The reference's join phase feeds pv-merged batches whose ``rank_offset``
encodes each ad's rank and its peers' positions; RankAttention mixes
features across the pv before the final logit (box_wrapper.h RankAttention
+ rank_attention_op.cu). Here that is one wrapper usable around any base
model with ``init``/``apply`` (DeepFM, WideDeep, ...), consumed by
bench.py's PBOX_BENCH_PV mode and the pv-phase tests.
"""

from __future__ import annotations

import jax

from paddlebox_tpu.ops.ctr_ops import rank_attention


class RankDeepFM:
    """Base model + rank_attention tower over the pv rank matrix."""

    def __init__(self, base, in_dim: int, max_rank: int = 3):
        self.base = base
        self.max_rank = max_rank
        self.in_dim = in_dim

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {
            "base": self.base.init(k1),
            "rank_param": 0.01
            * jax.random.normal(
                k2, (self.max_rank * self.max_rank * self.in_dim, 1)
            ),
        }

    def apply(self, params, slot_feats, dense=None, rank_offset=None):
        logit = self.base.apply(params["base"], slot_feats, dense)
        if rank_offset is not None:
            x = slot_feats.reshape(slot_feats.shape[0], -1)
            att = rank_attention(
                x, rank_offset, params["rank_param"], self.max_rank
            )
            logit = logit + att[:, 0]
        return logit
