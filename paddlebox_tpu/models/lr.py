"""Logistic regression over slot features — the smallest CTR config.

(BASELINE.md config 1: LR on Criteo-Kaggle.) The sparse first-order weight is
the table's embed_w column (index cvm_offset-1 of the pulled record), summed
per instance by the seqpool; the model just adds a dense linear + bias.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import linear_apply, linear_init


class LogisticRegression:
    def __init__(self, num_slots: int, feat_width: int, dense_dim: int = 0, embed_w_col: int = 2):
        self.num_slots = num_slots
        self.feat_width = feat_width
        self.dense_dim = dense_dim
        self.embed_w_col = embed_w_col

    def init(self, rng):
        params = {"b": jnp.zeros(())}
        if self.dense_dim:
            params["dense"] = linear_init(rng, self.dense_dim, 1)
        return params

    def apply(self, params, slot_feats, dense=None):
        first_order = jnp.sum(slot_feats[:, :, self.embed_w_col], axis=1)
        logit = first_order + params["b"]
        if self.dense_dim and dense is not None:
            logit = logit + linear_apply(params["dense"], dense)[:, 0]
        return logit
