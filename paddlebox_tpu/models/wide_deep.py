"""Wide&Deep and DCN (Deep & Cross Network) CTR models.

Same contract as DeepFM: ``apply(params, slot_feats [B, S, F], dense)`` ->
logits [B]; built from wide batched matmuls that tile onto the MXU. These
are the standard CTR baselines users of the reference build with
fluid.layers (fc / contrib CTR ops); here they are plain pytree models.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import linear_apply, linear_init, mlp_apply, mlp_init


class WideDeep:
    """Wide: first-order embed_w sum (+ dense linear). Deep: MLP tower."""

    def __init__(
        self,
        num_slots: int,
        feat_width: int,
        dense_dim: int = 0,
        hidden: Sequence[int] = (512, 256, 128),
        embed_w_col: int = 2,
    ):
        self.num_slots = num_slots
        self.feat_width = feat_width
        self.dense_dim = dense_dim
        self.hidden = tuple(hidden)
        self.embed_w_col = embed_w_col

    def init(self, rng):
        k_mlp, k_out, k_dense = jax.random.split(rng, 3)
        in_dim = self.num_slots * self.feat_width + self.dense_dim
        params = {
            "mlp": mlp_init(k_mlp, in_dim, self.hidden),
            "out": linear_init(k_out, self.hidden[-1], 1),
            "b": jnp.zeros(()),
        }
        if self.dense_dim:
            params["wide_dense"] = linear_init(k_dense, self.dense_dim, 1)
        return params

    def apply(self, params, slot_feats, dense=None):
        B = slot_feats.shape[0]
        wide = jnp.sum(slot_feats[:, :, self.embed_w_col], axis=1)  # [B]
        deep_in = slot_feats.reshape(B, -1)
        if self.dense_dim and dense is not None:
            deep_in = jnp.concatenate([deep_in, dense], axis=1)
        h = mlp_apply(params["mlp"], deep_in, final_activation=True)
        deep = linear_apply(params["out"], h)[:, 0]
        logit = params["b"] + wide + deep
        if self.dense_dim and dense is not None:
            logit = logit + linear_apply(params["wide_dense"], dense)[:, 0]
        return logit


class DCN:
    """Deep & Cross: explicit feature crosses x_{l+1} = x0*(x_l.w)+b+x_l
    alongside a deep tower, fused head."""

    def __init__(
        self,
        num_slots: int,
        feat_width: int,
        dense_dim: int = 0,
        n_cross: int = 3,
        hidden: Sequence[int] = (256, 128),
    ):
        self.num_slots = num_slots
        self.feat_width = feat_width
        self.dense_dim = dense_dim
        self.n_cross = n_cross
        self.hidden = tuple(hidden)
        self.in_dim = num_slots * feat_width + dense_dim

    def init(self, rng):
        params = {"cross_w": [], "cross_b": []}
        for _ in range(self.n_cross):
            rng, k = jax.random.split(rng)
            params["cross_w"].append(
                jax.random.normal(k, (self.in_dim,)) * (self.in_dim ** -0.5)
            )
            params["cross_b"].append(jnp.zeros((self.in_dim,)))
        rng, k_mlp, k_out = jax.random.split(rng, 3)
        params["mlp"] = mlp_init(k_mlp, self.in_dim, self.hidden)
        params["out"] = linear_init(k_out, self.hidden[-1] + self.in_dim, 1)
        return params

    def apply(self, params, slot_feats, dense=None):
        B = slot_feats.shape[0]
        x0 = slot_feats.reshape(B, -1)
        if self.dense_dim and dense is not None:
            x0 = jnp.concatenate([x0, dense], axis=1)
        x = x0
        for w, b in zip(params["cross_w"], params["cross_b"]):
            # x0 * (x . w) + b + x : rank-1 cross, O(B*d)
            x = x0 * (x @ w)[:, None] + b + x
        h = mlp_apply(params["mlp"], x0, final_activation=True)
        fused = jnp.concatenate([x, h], axis=1)
        return linear_apply(params["out"], fused)[:, 0]
