"""MMoE: multi-gate mixture-of-experts for multi-task CTR.

The reference's two-phase join/update training often carries multi-task
heads (click, conversion/PCOC q-values — cvm_offset 8 layouts) over shared
embeddings; MMoE is the standard dense tower for that (SURVEY.md §7 step 10
"MMoE/multi-phase"). Experts are one batched [E, in, h] matmul (vmapped —
one MXU call, not E small ones); per-task softmax gates mix expert outputs.

``apply`` returns [B, n_tasks] logits; single-task users take ``[:, 0]`` or
wrap with ``task_head(model, i)`` to fit the scalar-logit train step.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import linear_apply, linear_init, mlp_apply, mlp_init


class MMoE:
    def __init__(
        self,
        num_slots: int,
        feat_width: int,
        dense_dim: int = 0,
        n_experts: int = 4,
        n_tasks: int = 2,
        expert_hidden: Sequence[int] = (128, 64),
        tower_hidden: Sequence[int] = (32,),
    ):
        self.num_slots = num_slots
        self.feat_width = feat_width
        self.dense_dim = dense_dim
        self.n_experts = n_experts
        self.n_tasks = n_tasks
        self.expert_hidden = tuple(expert_hidden)
        self.tower_hidden = tuple(tower_hidden)
        self.in_dim = num_slots * feat_width + dense_dim

    def init(self, rng):
        keys = jax.random.split(rng, self.n_experts + 2 * self.n_tasks + 1)
        experts = [
            mlp_init(keys[e], self.in_dim, self.expert_hidden)
            for e in range(self.n_experts)
        ]
        # stack expert layers: list over depth of {"w": [E,i,o], "b": [E,o]}
        stacked = [
            {
                "w": jnp.stack([experts[e][l]["w"] for e in range(self.n_experts)]),
                "b": jnp.stack([experts[e][l]["b"] for e in range(self.n_experts)]),
            }
            for l in range(len(self.expert_hidden))
        ]
        gates = [
            linear_init(keys[self.n_experts + t], self.in_dim, self.n_experts)
            for t in range(self.n_tasks)
        ]
        towers = []
        for t in range(self.n_tasks):
            k = keys[self.n_experts + self.n_tasks + t]
            k1, k2 = jax.random.split(k)
            towers.append(
                {
                    "mlp": mlp_init(k1, self.expert_hidden[-1], self.tower_hidden),
                    "out": linear_init(k2, self.tower_hidden[-1], 1),
                }
            )
        return {"experts": stacked, "gates": gates, "towers": towers}

    def apply(self, params, slot_feats, dense=None):
        B = slot_feats.shape[0]
        x = slot_feats.reshape(B, -1)
        if self.dense_dim and dense is not None:
            x = jnp.concatenate([x, dense], axis=1)

        # all experts in one batched matmul chain: h [E, B, h_l]
        h = jnp.broadcast_to(x[None], (self.n_experts,) + x.shape)
        for l, layer in enumerate(params["experts"]):
            h = jnp.einsum("ebi,eio->ebo", h, layer["w"]) + layer["b"][:, None]
            h = jax.nn.relu(h)
        expert_out = jnp.einsum("ebh->beh", h)  # [B, E, h]

        logits = []
        for t in range(self.n_tasks):
            g = jax.nn.softmax(linear_apply(params["gates"][t], x), axis=-1)  # [B, E]
            mixed = jnp.einsum("be,beh->bh", g, expert_out)
            ht = mlp_apply(params["towers"][t]["mlp"], mixed, final_activation=True)
            logits.append(linear_apply(params["towers"][t]["out"], ht)[:, 0])
        return jnp.stack(logits, axis=1)  # [B, n_tasks]


def task_head(model: MMoE, task: int):
    """Adapter: scalar-logit view of one task for the CTR train step."""

    class _Head:
        def init(self, rng):
            return model.init(rng)

        def apply(self, params, slot_feats, dense=None):
            return model.apply(params, slot_feats, dense)[:, task]

    return _Head()
