from paddlebox_tpu.models.layers import mlp_init, mlp_apply, linear_init, linear_apply
from paddlebox_tpu.models.lr import LogisticRegression
from paddlebox_tpu.models.deepfm import DeepFM

__all__ = [
    "mlp_init",
    "mlp_apply",
    "linear_init",
    "linear_apply",
    "LogisticRegression",
    "DeepFM",
]
