from paddlebox_tpu.models.layers import mlp_init, mlp_apply, linear_init, linear_apply
from paddlebox_tpu.models.lr import LogisticRegression
from paddlebox_tpu.models.deepfm import DeepFM
from paddlebox_tpu.models.wide_deep import WideDeep, DCN
from paddlebox_tpu.models.mmoe import MMoE, task_head
from paddlebox_tpu.models.rank import RankDeepFM

__all__ = [
    "mlp_init",
    "mlp_apply",
    "linear_init",
    "linear_apply",
    "LogisticRegression",
    "DeepFM",
    "WideDeep",
    "DCN",
    "MMoE",
    "task_head",
    "RankDeepFM",
]
