"""Minimal functional NN layers for the CTR dense towers.

Plain pytree params + pure apply functions — everything stays jit/grad/shard
friendly with zero framework ceremony. Matmuls are kept batched and wide so
XLA tiles them onto the MXU; bf16 activation compute with fp32 params is the
default precision recipe.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp


def linear_init(rng, in_dim: int, out_dim: int, scale: str = "xavier") -> Dict[str, Any]:
    wkey, _ = jax.random.split(rng)
    if scale == "xavier":
        s = jnp.sqrt(2.0 / (in_dim + out_dim))
    else:
        s = 0.01
    return {
        "w": jax.random.normal(wkey, (in_dim, out_dim), jnp.float32) * s,
        "b": jnp.zeros((out_dim,), jnp.float32),
    }


def linear_apply(p: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
    return x @ p["w"] + p["b"]


def mlp_init(rng, in_dim: int, hidden: Sequence[int]) -> List[Dict[str, Any]]:
    layers = []
    dims = [in_dim, *hidden]
    for i in range(len(hidden)):
        rng, sub = jax.random.split(rng)
        layers.append(linear_init(sub, dims[i], dims[i + 1]))
    return layers


def mlp_apply(
    layers: List[Dict[str, Any]],
    x: jnp.ndarray,
    final_activation: bool = False,
    compute_dtype=jnp.bfloat16,
) -> jnp.ndarray:
    """ReLU MLP; activations in bf16 (MXU-native), params fp32."""
    h = x.astype(compute_dtype)
    for i, p in enumerate(layers):
        h = h @ p["w"].astype(compute_dtype) + p["b"].astype(compute_dtype)
        if i < len(layers) - 1 or final_activation:
            h = jax.nn.relu(h)
    return h.astype(jnp.float32)
