"""CTR model interface.

A model consumes the per-slot pooled+CVM'd features (output of
fused_seqpool_cvm: ``[batch, num_slots, feat_width]`` where
``feat_width = cvm_offset + embedx_dim`` in the join phase) plus an optional
dense float block, and produces one logit per instance (or per task).

This replaces the reference's static-graph model building
(fluid.layers._pull_box_sparse + fused_seqpool_cvm + fc stacks,
python/paddle/fluid/layers/nn.py:680, contrib/layers/nn.py:1337-2350) with
plain init/apply pairs over pytrees.
"""

from __future__ import annotations

from typing import Any, Protocol

import jax.numpy as jnp


class CTRModel(Protocol):
    num_slots: int
    feat_width: int
    dense_dim: int

    def init(self, rng) -> Any:  # params pytree
        ...

    def apply(self, params: Any, slot_feats: jnp.ndarray, dense: jnp.ndarray | None) -> jnp.ndarray:
        """-> logits [batch] (or [batch, n_tasks] for multi-task models)."""
        ...
