"""Global flag registry with environment passthrough.

Parity with the reference's gflags knobs (paddle/fluid/platform/flags.cc:477-607
defines the padbox_* family; global_value_getter_setter.cc exposes runtime
get/set). Flags are declared once with a type and default; the environment
variable ``PBOX_<UPPER_NAME>`` overrides the default at first read.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
_defs: Dict[str, tuple] = {}  # guarded-by: _lock  (name -> (type_fn, default, help, validator))
_values: Dict[str, Any] = {}  # guarded-by: _lock


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def define_flag(
    name: str,
    default: Any,
    help: str = "",
    validator: Optional[Callable[[Any], Any]] = None,
) -> None:
    """Declare a flag. ``validator`` (if given) runs on every set_flag and
    on the first env-sourced read, and must raise on an invalid value — a
    typo'd enum flag fails at the set site, not as a silent fallthrough
    wherever the value is eventually consumed."""
    type_fn: Callable
    if isinstance(default, bool):
        type_fn = _parse_bool
    elif isinstance(default, int):
        type_fn = int
    elif isinstance(default, float):
        type_fn = float
    else:
        type_fn = str
    with _lock:
        _defs[name] = (type_fn, default, help, validator)


def get_flag(name: str) -> Any:
    with _lock:
        if name in _values:
            return _values[name]
        if name not in _defs:
            raise KeyError(f"undefined flag: {name}")
        type_fn, default, _, validator = _defs[name]
        env = os.environ.get("PBOX_" + name.upper())
    # parse + validate OUTSIDE the lock: validators may import their
    # consumer module (e.g. ops/wire_quant), whose import-time flag reads
    # would deadlock on the non-reentrant registry lock
    val = type_fn(env) if env is not None else default
    if validator is not None and env is not None:
        validator(val)
    with _lock:
        return _values.setdefault(name, val)


def set_flag(name: str, value: Any) -> None:
    with _lock:
        if name not in _defs:
            raise KeyError(f"undefined flag: {name}")
        type_fn, _, _, validator = _defs[name]
    val = type_fn(value)
    if validator is not None:
        validator(val)
    with _lock:
        _values[name] = val


def all_flags() -> Dict[str, Any]:
    with _lock:
        names = list(_defs)
    return {n: get_flag(n) for n in names}


# --- data pipeline (reference: flags.cc padbox_* family) ---
# (knobs from the reference's padbox_* family are declared HERE only once a
# consumer reads them — pbox-lint REG003 flags defined-never-read knobs)
define_flag("enable_native_parser", True, "use the C++ slot parser fast path when eligible")
define_flag("sample_rate", 1.0, "line sampling rate on read (BufferedLineFileReader parity)")

# --- wire formats (ops/wire_quant.py; defined here so consumers can read
# them without importing that module first) ---
def _validate_wire_dtype(mode: str) -> None:
    # lazy: wire_quant imports config at module load (flag reads), so a
    # top-level import here would be circular
    from paddlebox_tpu.ops import wire_quant

    wire_quant._check(mode)


def _validate_ici_wire_dtype(mode: str) -> None:
    from paddlebox_tpu.ops import wire_quant

    wire_quant.check_ici(mode)


define_flag(
    "wire_dtype",
    "fp32",
    "value format on the host<->device boundary wire (carrier splice "
    "uploads, departing-slice fetch, flush, classic device writeback): "
    "fp32 | bf16 | int8 (int8 = per-row-scaled embed block + bf16 rest)",
    validator=_validate_wire_dtype,
)
define_flag(
    "ici_wire_dtype",
    "fp32",
    "value format of the sharded pull/push all_to_all payloads over ICI: "
    "fp32 | bf16 | int8 | adaptive (bf16/int8 keep the show/clk counter "
    "columns fp32; int8 carries one per-record max-abs scale; adaptive "
    "rides hot rows bf16 and the cold tail int8 — see ici_hot_frac / "
    "ici_hot_show / ici_wire_adaptive)",
    validator=_validate_ici_wire_dtype,
)
define_flag(
    "ici_wire_adaptive",
    True,
    "master ablation gate for ici_wire_dtype=adaptive: when False the "
    "adaptive mode degrades to fp32 and no hotness plumbing runs, so the "
    "wire (and every downstream bit) is identical to the pre-adaptive "
    "default — the bitwise off-ablation the convergence gates compare "
    "against",
)
define_flag(
    "ici_hot_frac",
    0.125,
    "static per-bucket hot-slot bound for the adaptive ICI wire: the "
    "first round(frac*K) slots of each per-shard request bucket ride "
    "bf16, the rest int8. Static so the all_to_all keeps one compiled "
    "shape; hot keys beyond the bound ride the int8 region (counted "
    "under wire.ici_hot_overflow_keys). 0 degrades to uniform int8, "
    "1 to uniform bf16 — both bitwise",
)
define_flag(
    "ici_hot_show",
    1.0,
    "decayed-show threshold above which a key counts as hot for the "
    "adaptive ICI wire (same scale as spill_pin_show: the tier's "
    "per-row decayed show column). Keys on the disk tier or not yet "
    "created read 0 = cold",
)
define_flag(
    "host_wire_codec",
    True,
    "host-plane wire codec (ops/host_codec.py): delta+varint key streams "
    "in the working-set exchange and chunked-zlib PBTX v3 frame payloads. "
    "False is the raw ablation — bitwise-identical results, more bytes "
    "(wire.host_raw_bytes_* vs wire.host_bytes_* measures the cut)",
)
define_flag(
    "host_compress_level",
    1,
    "zlib level for PBTX v3 frame payloads (1 = fastest: the codec runs "
    "on the sender's worker thread and must outrun the socket to win)",
)
define_flag(
    "host_compress_min_bytes",
    512,
    "frames smaller than this ship raw: below it the zlib+chunk-table "
    "overhead eats the win and the codec byte already marks them raw",
)

# --- sparse table ---
define_flag("sparse_table_shard_bits", 6, "log2 host shards in the tiered store")
define_flag("enable_pullpush_dedup_keys", True, "dedup keys across slots before pull (reference flags.cc:603)")

# --- batch / device ---
define_flag(
    "batch_bucket_rounding",
    2048,
    "flat key-count buckets rounded to multiples of this. Also the lever "
    "against compile-cache growth on long daily runs: pad shapes that "
    "repeat across passes HIT jax's compilation cache, drifting shapes "
    "miss it (~tens of host MB per distinct shape set; measured flat RSS "
    "at fixed shapes over a 14-pass soak)",
)
define_flag("use_pallas_sparse", False, "Pallas prefetch-DMA kernels for sparse pull/push on TPU")
define_flag(
    "kernel_plan_path",
    "auto",
    "kernel-plan artifact routing pallas-vs-native per (op, backend, "
    "shape bucket) — 'auto' uses the committed tools/kernel_plan.json when "
    "present, 'off' forces the builtin defaults (which honor "
    "use_pallas_sparse), anything else is an explicit plan file path "
    "(see ops/kernel_plan.py; regenerate with tools/tune_kernels.py)",
)

# --- host transport (parallel/transport.py) ---
define_flag(
    "transport_send_retries",
    3,
    "reconnect+resend attempts after a failed host-plane send before the "
    "error surfaces to the caller (each retry re-opens the peer connection "
    "and replays every un-acked frame)",
)
define_flag(
    "transport_backoff_s",
    0.1,
    "base of the exponential backoff between transport send retries "
    "(doubles per attempt, capped at 5s)",
)
define_flag(
    "transport_heartbeat_s",
    2.0,
    "interval of the per-peer heartbeat thread: each beat carries the "
    "delivered-frame ack that prunes the sender's resend buffer and feeds "
    "the failure detector; 0 disables the thread (no failure detection, "
    "resend buffers grow until reconnect)",
)
define_flag(
    "transport_peer_dead_s",
    15.0,
    "failure-detector horizon: a peer silent for half this is 'suspect', "
    "for all of it 'dead' — collectives stop waiting on dead peers and "
    "name them instead of running out the full timeout",
)

# --- serving plane (serve/) ---
define_flag(
    "serve_poll_interval_s",
    0.05,
    "follower watermark poll period: how often serve/follower.py re-reads "
    "latest.json looking for newly published deltas (the freshness half of "
    "the freshness/latency tradeoff — see docs/SERVING.md)",
)
define_flag(
    "serve_row_bucket",
    256,
    "request working-set capacity rounds to multiples of this before the "
    "compiled forward (serve-side analog of batch_bucket_rounding: bounds "
    "the distinct table shapes XLA compiles for, at the cost of padded "
    "gather rows)",
)
define_flag(
    "serve_key_bucket",
    256,
    "flat key-count padding bucket for score batches (the pack_batch "
    "bucket the scorer uses; smaller than the training default because "
    "serving batches are request-sized, not pass-sized)",
)
define_flag(
    "serve_batch_wait_ms",
    2.0,
    "max time the score server holds an under-full batch open waiting for "
    "more requests before scoring it (the latency half of the tradeoff: 0 "
    "scores every request alone, larger values amortize the compiled step)",
)
define_flag(
    "serve_require_manifest",
    True,
    "follower refuses snapshots without a manifest.json (legacy pre-"
    "manifest trees need False; the trainer-side resume path stays lenient "
    "either way)",
)
define_flag(
    "serve_request_timeout_ms",
    30000.0,
    "default per-request deadline for score requests, honored by the "
    "in-process ScoreServer.score wrapper (a wedged batcher surfaces as a "
    "typed ServeTimeoutError instead of blocking the caller forever) and "
    "used as the fleet client's default end-to-end budget",
)
define_flag(
    "serve_shed_queue_depth",
    256,
    "load-shedding threshold: a score submit arriving while the batcher "
    "queue already holds this many requests is refused with the typed "
    "ServeOverloadError (counted under serve.shed_requests) instead of "
    "growing an unbounded backlog; 0 disables shedding",
)
define_flag(
    "serve_health_beat_s",
    0.25,
    "cadence of each fleet follower's ctl:serve:health gossip beat to the "
    "front-end client (state, chain position, staleness, queue depth)",
)
define_flag(
    "serve_health_dead_s",
    2.0,
    "fleet-view freshness horizon: a follower whose last health beat is "
    "older than this is treated as dead by the load-balancing client and "
    "not queried (independent of the transport failure detector)",
)
define_flag(
    "serve_lag_deltas",
    2,
    "staleness gossip threshold: a follower whose applied delta_idx "
    "trails the fleet's freshest (same ownership epoch) by more than this "
    "many deltas is marked lagging and not queried until it catches up",
)
define_flag(
    "serve_hedge_ms",
    250.0,
    "hedged-dispatch trigger: when the primary follower has not answered "
    "within this budget (p99 about to blow), the fleet client re-sends "
    "the same request to a second healthy follower and takes the first "
    "answer; 0 disables hedging",
)
define_flag(
    "serve_client_retries",
    3,
    "bounded retry budget of the fleet client: attempts beyond the first "
    "pick a different follower with exponential backoff before the typed "
    "ServeRequestError surfaces to the caller",
)
define_flag(
    "serve_client_backoff_s",
    0.05,
    "base of the exponential backoff between fleet-client retry attempts "
    "(doubles per attempt)",
)
define_flag(
    "fleet_stage_dir",
    "",
    "host-local staging directory the fleet stager mirrors the published "
    "base+delta chain into — N followers on the host tail the stage, so "
    "the origin checkpoint root is fetched once per publish, not N times "
    "(empty: the FleetStage caller must pass an explicit directory)",
)


def _validate_device_scoring_tier(v: str) -> None:
    if v not in ("off", "on"):
        raise ValueError(
            f"device_scoring_tier must be 'off' or 'on', got {v!r}"
        )


define_flag(
    "device_scoring_tier",
    "off",
    "mesh-sharded device-resident hot-key scoring tier: 'on' builds a "
    "NamedSharding-placed copy of the hottest rows at every version "
    "commit (decayed-show >= device_tier_hot_show) and answers serve "
    "lookups from it through the sharded-pull path, falling back to the "
    "host TableVersion.lookup_rows only on tier misses; 'off' (the "
    "ablation) is bitwise-identical to the host-only serving path",
    validator=_validate_device_scoring_tier,
)
define_flag(
    "device_tier_hot_show",
    1.0,
    "decayed-show threshold a row must clear at commit time to enter the "
    "device scoring tier (same shows_peek signal the adaptive ICI wire "
    "uses; lower admits more of the tail, higher keeps HBM for the head)",
)
define_flag(
    "device_tier_capacity",
    65536,
    "max rows the device scoring tier holds per version; when more rows "
    "clear device_tier_hot_show, the hottest ones win (top-k by decayed "
    "show) and the rest serve from the host path",
)
define_flag(
    "serve_lb_least_loaded",
    True,
    "fleet-client load balancing: weigh the round-robin pick against the "
    "next candidate by gossiped queue depth (least-loaded-of-two, "
    "reroutes counted under serve.lb_rerouted); False is the pure "
    "round-robin ablation",
)

# --- streaming plane (train/stream.py) ---
def _validate_positive(v) -> None:
    if not v > 0:
        raise ValueError(f"flag value must be > 0, got {v!r}")


def _validate_stretch(v) -> None:
    if not v >= 1:
        raise ValueError(f"stream_backlog_max_stretch must be >= 1, got {v!r}")


define_flag(
    "stream_micro_pass_s",
    60.0,
    "time budget per streaming micro-pass: the StreamSupervisor collects "
    "tailed records for this long, then cuts them into one pass and "
    "publishes a delta through the normal watermark path (the minute-level "
    "cadence of ROADMAP item 2; the freshness SLO is roughly this plus "
    "train+publish+poll time)",
    validator=_validate_positive,
)
define_flag(
    "stream_poll_interval_s",
    1.0,
    "tail-follow poll period inside a micro-pass window: how often the "
    "DirectoryTailer re-scans the append-only dataset dir for grown or "
    "new files",
    validator=_validate_positive,
)
define_flag(
    "stream_compact_every",
    60,
    "micro-deltas between chain compactions: every N streamed publishes "
    "the manager folds base+delta-0001..N into one compact snapshot so a "
    "late follower's catch-up applies O(hours) artifacts, not O(minutes-"
    "since-base) (CheckpointManager.compact; <= 1 disables)",
)
define_flag(
    "stream_backlog_max_stretch",
    8.0,
    "graceful-degradation cap on the micro-pass cadence: when a cut takes "
    "longer than its budget (ingest backlog), the effective window doubles "
    "per overrun (counted under stream.backlog_stretches) up to budget * "
    "this factor, and shrinks back once cuts run under half budget",
    validator=_validate_stretch,
)

# --- metrics ---
define_flag("auc_num_buckets", 1_000_000, "AUC wuauc bucket table size (reference box_wrapper.h:61)")
define_flag("auc_runner_pool_size", 10_000, "AucRunner candidate reservoir capacity per pool")
