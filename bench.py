"""Headline benchmark: END-TO-END CTR training throughput, samples/sec/chip.

Times ``CTRTrainer.train_pass`` wall-clock at the flagship DeepFM shape —
everything between "records in memory" and "trained table": native batch
pack (C++ ragged gather + dedup), background packer threads, host->device
upload, and the jitted device step (sparse pull -> fused seqpool+CVM ->
DeepFM fwd/bwd -> sparse adagrad push -> dense adam -> online AUC). This is
the full BoxPSWorker::TrainFiles loop (boxps_worker.cc:420-466) including
the data-feed half the reference runs in MiniBatchGpuPack worker threads
(data_feed.h:1418-1542) — not just the device program.

Load (file parse) and pass finalize times are reported as sub-fields; the
headline metric matches the reference's definition of training throughput
(records consumed per second while the trainer runs).

Baseline (BASELINE.json): 1M samples/sec on 64 chips => 15625 samples/sec/chip.
Prints ONE json line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

# Criteo-DeepFM-ish flagship shape (BASELINE.md config 3)
NUM_SLOTS = 39
EMBEDX_DIM = 16
BATCH = 4096
HIDDEN = (512, 256, 128)
N_FILES = 16
RECORDS_PER_FILE = 8192  # 131072 records = 32 batches per epoch
KEY_SPACE = 1 << 22
TRAIN_BATCHES = 96  # 3 epochs over the pass (wrap-around, lockstep parity)
BASELINE_PER_CHIP = 1_000_000 / 64


def _logkey(search_id: int, cmatch: int, rank: int) -> str:
    """Reference logkey layout (data_feed.cc SlotRecord parse): 11 pad chars,
    3-hex cmatch, 2-hex rank, 16-hex search_id."""
    return (
        "0" * 11
        + format(cmatch, "03x")
        + format(rank, "02x")
        + format(search_id, "016x")
    )


def write_files(tmpdir: str, rng, reuse_pool=None, prefix="part", pv=False) -> tuple:
    """Synthetic slot-format text at CTR-ish shapes: one key per slot drawn
    zipf-ish (hot head + uniform tail), binary label.

    ``reuse_pool``: previous pass's cold-key pool — 75% of cold draws come
    from it, modeling the high day-over-day key recurrence of real CTR
    streams (the regime the device-carried pass boundary exploits).
    ``pv``: prepend a logkey column grouping consecutive records into
    queries of 1-4 ads, so the join phase (PvMerge) has real pv structure.
    Returns (files, cold key pool of this pass)."""
    files = []
    pool_parts = []
    search_id = 1
    for fi in range(N_FILES):
        n = RECORDS_PER_FILE
        hot = rng.integers(1, 1 << 12, (n, NUM_SLOTS))
        cold = rng.integers(1, KEY_SPACE, (n, NUM_SLOTS))
        if reuse_pool is not None:
            recur = reuse_pool[rng.integers(0, len(reuse_pool), (n, NUM_SLOTS))]
            cold = np.where(rng.random((n, NUM_SLOTS)) < 0.75, recur, cold)
        take_hot = rng.random((n, NUM_SLOTS)) < 0.25
        keys = np.where(take_hot, hot, cold)
        pool_parts.append(keys[~take_hot])
        labels = (rng.random(n) < 0.2).astype(np.int32)
        logkeys = None
        if pv:
            # group rows into queries: 1-4 ads per pv, ranks 1..n_ads
            logkeys = []
            i = 0
            while i < n:
                n_ads = int(rng.integers(1, 5))
                for r in range(1, min(n_ads, n - i) + 1):
                    logkeys.append(_logkey(search_id, 222, r))
                search_id += 1
                i += n_ads
        path = os.path.join(tmpdir, f"{prefix}-{fi:03d}.txt")
        with open(path, "w") as f:
            for i in range(n):
                row = keys[i]
                head = f"1 {logkeys[i]} " if pv else ""
                f.write(
                    head
                    + f"1 {labels[i]}.0 "
                    + " ".join(f"1 {k}" for k in row)
                    + "\n"
                )
        files.append(path)
    return files, np.concatenate(pool_parts)


def apply_legacy_init_env() -> None:
    """Map the historical PBOX_BENCH_INIT_* env knobs onto the
    backendguard flags. The probe/retry/fallback logic that grew here now
    lives in utils/backendguard.py (shared by every entrypoint); older
    drivers and tools/tpu_capture.py still speak the bench-era env names:
      PBOX_BENCH_INIT_RETRIES  -> backend_init_retries   (default 6)
      PBOX_BENCH_INIT_TIMEOUT  -> backend_init_timeout_s (default 120s)
      PBOX_BENCH_INIT_BACKOFF  -> backend_init_backoff_s (default 30s)
    """
    from paddlebox_tpu import config as _config

    for env, flag in (
        ("PBOX_BENCH_INIT_TIMEOUT", "backend_init_timeout_s"),
        ("PBOX_BENCH_INIT_RETRIES", "backend_init_retries"),
        ("PBOX_BENCH_INIT_BACKOFF", "backend_init_backoff_s"),
    ):
        if env in os.environ:
            _config.set_flag(flag, os.environ[env])


LAST_GOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools", "last_good_tpu_bench.json")
CAPTURE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "last_good_tpu_capture.json")
CAPTURE_LOCK_PATH = CAPTURE_PATH + ".lock"  # shared with tools/tpu_capture.py
PROBE_LOOP_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools", "tpu_probe_log.jsonl")


def pv_mode_enabled() -> bool:
    """PBOX_BENCH_PV=1 benches the JOIN phase: pv-merged batches with
    rank_offset through the rank-attention tower (the two-phase join/update
    pipeline's other half; EnablePvMerge branch, data_feed.cc:2165-2198)."""
    return os.environ.get("PBOX_BENCH_PV", "0") == "1"


def bench_config_id() -> str:
    """Identity of the measured workload: a cached last-good number is only
    comparable to runs of the SAME bench definition."""
    return (
        f"slots={NUM_SLOTS},emb={EMBEDX_DIM},B={BATCH},hid={HIDDEN},"
        f"files={N_FILES}x{RECORDS_PER_FILE},keys={KEY_SPACE},"
        f"batches={TRAIN_BATCHES}"
        + (",pv=1" if pv_mode_enabled() else "")
    )


def read_last_good():
    """Most recent successful TPU measurement, cached on disk by main()."""
    try:
        with open(LAST_GOOD_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_last_capture():
    """Most recent FULL capture artifact (tools/tpu_capture.py): headline +
    knob sweep + wire/carrier/pv ablations + scatter sweep, taken by the
    background probe loop on the first healthy chip window. Embedded in the
    fallback JSON so a wedged driver run still carries the measured TPU
    evidence."""
    try:
        with open(CAPTURE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_probe_loop_tail(n: int = 30):
    """Tail of the long-running background probe log (tools/tpu_probe_loop.sh),
    if one was kept during the build session — independent wedge evidence
    spanning hours, not just this bench invocation."""
    try:
        with open(PROBE_LOOP_LOG) as f:
            lines = f.read().strip().splitlines()
    except OSError:
        return None
    out = []
    for ln in lines[-n:]:
        try:
            out.append(json.loads(ln))
        except ValueError:
            pass
    return out or None


def _plan_source() -> str:
    """Provenance of the active kernel plan ("builtin defaults" or the
    artifact path), for the bench JSON record."""
    from paddlebox_tpu.ops.kernel_plan import get_plan

    return get_plan().source


def fail_fast(reason: str) -> None:
    print(
        json.dumps(
            {
                "metric": "deepfm_e2e_train_samples_per_sec_per_chip",
                "value": 0.0,
                "unit": "samples/s/chip",
                "vs_baseline": 0.0,
                "error": reason,
            }
        )
    )
    sys.exit(1)


def wait_for_capture_lock() -> None:
    """If a tpu_capture.py run is in flight (lock file with a live pid),
    wait for it instead of racing it: two benches sharing one chip and one
    host core degrade BOTH numbers. Skipped inside the capture itself
    (PBOX_BENCH_NO_LOCK_WAIT) and bounded so a driver-budgeted run is
    never starved — after the wait the capture artifact is fresh and this
    run either measures a free chip or embeds the capture."""
    if os.environ.get("PBOX_BENCH_NO_LOCK_WAIT", "0") == "1":
        return
    lock = CAPTURE_LOCK_PATH
    budget = float(os.environ.get("PBOX_BENCH_CAPTURE_WAIT", "2400"))
    t0 = time.time()
    warned = False
    while time.time() - t0 < budget:
        try:
            with open(lock) as f:
                pid = int(f.read().strip() or "0")
        except (OSError, ValueError):
            return
        if pid <= 0:
            return  # truncated/garbage lock: os.kill(0, ...) would probe
            # our own process group and "succeed" forever
        try:
            os.kill(pid, 0)  # liveness probe, no signal delivered
        except ProcessLookupError:
            return  # stale lock
        except PermissionError:
            pass  # pid EXISTS under another uid: the capture is live, wait
        if not warned:
            print(
                f"bench: capture in flight (pid {pid}), waiting up to "
                f"{budget:.0f}s for it to finish",
                file=sys.stderr, flush=True,
            )
            warned = True
        time.sleep(15)


def main():
    profile = "--profile" in sys.argv
    wait_for_capture_lock()
    apply_legacy_init_env()
    from paddlebox_tpu.utils.backendguard import ensure_backend

    try:
        verdict = ensure_backend()
    except Exception as e:  # even the CPU fallback failed: diagnose fast
        fail_fast(f"backend bring-up failed: {e!r}")
    info = {"platform": verdict.platform, "n_devices": verdict.n_devices}
    probe_log = verdict.probe_log
    tpu_error = verdict.error if verdict.wedged else None

    import jax
    import optax

    # persistent XLA compile cache: PBOX_COMPILE_CACHE_DIR (or the
    # compile_cache_dir flag) points at a durable directory; "auto" stays
    # off here — bench owns no checkpoint root (the supervisor resolves
    # "auto" under its own). Enabled before any compilation so warmup_s
    # becomes a cold-vs-warm pair across consecutive runs.
    from paddlebox_tpu import config as _cfg
    from paddlebox_tpu.utils import compilecache

    cache_dir = compilecache.resolve_dir(str(_cfg.get_flag("compile_cache_dir")))
    if cache_dir is not None:
        compilecache.enable(cache_dir)

    from paddlebox_tpu.data import BoxPSDataset, SlotInfo, SlotSchema
    from paddlebox_tpu.models import DeepFM, RankDeepFM
    from paddlebox_tpu.table import (
        HostSparseTable,
        SparseOptimizerConfig,
        ValueLayout,
    )
    from paddlebox_tpu.train import CTRTrainer, TrainStepConfig
    from paddlebox_tpu.utils.monitor import STAT_GET
    from paddlebox_tpu.utils.monitor import all_histograms as _all_histograms

    pv = pv_mode_enabled()
    rng = np.random.default_rng(0)
    schema = SlotSchema(
        [SlotInfo("label", type="float", dense=True, dim=1)]
        + [SlotInfo(f"s{i}") for i in range(NUM_SLOTS)],
        label_slot="label",
        parse_logkey=pv,
    )
    layout = ValueLayout(embedx_dim=EMBEDX_DIM)
    opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0)
    table = HostSparseTable(layout, opt_cfg, n_shards=64, seed=0)

    with tempfile.TemporaryDirectory() as tmpdir:
        files, key_pool = write_files(tmpdir, rng, pv=pv)

        ds = BoxPSDataset(
            schema, table, batch_size=BATCH, shuffle_mode="local", seed=0
        )
        ds.set_filelist(files)
        t0 = time.perf_counter()
        ds.load_into_memory()
        load_s = time.perf_counter() - t0
        native_store = ds.store is not None

        t0 = time.perf_counter()
        ds.begin_pass(round_to=512)
        if pv:
            # join phase: group records into pvs, serve rank_offset batches
            # (max_rank must match the model's attention block count — the
            # generator emits ranks 1..4)
            ds.set_current_phase(1)
            ds.preprocess_instance(max_rank=4)
        finalize_s = time.perf_counter() - t0

        base = DeepFM(
            num_slots=NUM_SLOTS,
            feat_width=layout.pull_width,
            embedx_dim=EMBEDX_DIM,
            hidden=HIDDEN,
        )
        if pv:
            model = RankDeepFM(
                base, NUM_SLOTS * layout.pull_width, max_rank=4
            )
        else:
            model = base
        cfg = TrainStepConfig(
            num_slots=NUM_SLOTS,
            batch_size=BATCH,
            layout=layout,
            sparse_opt=opt_cfg,
            auc_buckets=100_000,
            model_takes_rank_offset=pv,
        )
        trainer = CTRTrainer(model, cfg, dense_opt=optax.adam(1e-3))
        trainer.init_params(jax.random.PRNGKey(0))

        # warmup: freeze pad shapes over the FULL timed partition (so no
        # shape growth -> recompile lands inside the timed region), then
        # train one superstep chunk to compile the scan-K program and prime
        # packer scratch.
        from paddlebox_tpu import config as _config

        # bf16 boundary wire: halves the departing-slice D2H and new-key
        # H2D at the carried boundary (AUC in the output guards quality)
        _config.set_flag(
            "wire_dtype", os.environ.get("PBOX_WIRE_DTYPE", "bf16")
        )
        # PBOX_BOUNDARY_PIPELINE=0 benches the sequential boundary (the
        # r05-and-earlier shape: sync end_pass, then load, then finalize)
        # so captures can ablate the pipelined handoff against it
        _config.set_flag(
            "boundary_pipeline",
            int(os.environ.get("PBOX_BOUNDARY_PIPELINE", "1")),
        )
        pipelined = bool(_config.get_flag("boundary_pipeline"))

        # next pass's input, written up front: the pipelined boundary kicks
        # its load into the background BEFORE the timed region so read/
        # premerge/prefetch overlap warmup + training (the overlap the
        # supervisor's prefetch kick provides in the day loop)
        files2, _ = write_files(
            tmpdir, rng, reuse_pool=key_pool, prefix="p2", pv=pv
        )
        if pipelined:
            ds.set_filelist(files2)
            ds.preload_into_memory()

        if pv:
            # join phase: pv feeds don't wrap, so warm with one full epoch
            # (compile + resident upload) and time two more over the pass
            t0 = time.perf_counter()
            trainer.prepare_pass(ds)
            trainer.train_pass(ds)
            warmup_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(2):
                out = trainer.train_pass(ds, profile=profile)
            train_s = time.perf_counter() - t0
            # count REAL instances (ghost/pad slots carry ins_weight 0 and
            # train nothing) so the join-phase number is comparable to the
            # flat headline, not inflated by pv padding
            timed_samples = 2 * ds.memory_data_size()
        else:
            t0 = time.perf_counter()
            trainer.prepare_pass(ds, n_batches=TRAIN_BATCHES)
            warm = max(4, int(_config.get_flag("resident_scan_batches")))
            trainer.train_pass(ds, n_batches=warm)
            # reported so the steady-state headline can't be mistaken for
            # cold-start: this is the resident upload + XLA compile + first
            # chunk (the reference's first-pass warmup is the same shape)
            warmup_s = time.perf_counter() - t0

            t0 = time.perf_counter()
            out = trainer.train_pass(
                ds, n_batches=TRAIN_BATCHES, profile=profile
            )
            train_s = time.perf_counter() - t0
            timed_samples = TRAIN_BATCHES * BATCH

        # pass boundary, measured as the HANDOFF BLOCKING TIME: how long
        # end_pass + the next begin_pass actually stall the trainer. The
        # pipelined boundary dispatches EndPass to a worker and adopts the
        # background-staged load (premerge + host prefetch already done),
        # so the stall shrinks to the dispatch + the splice/assemble that
        # genuinely must run on the handoff. The sequential ablation
        # (PBOX_BOUNDARY_PIPELINE=0) measures the r05 shape: sync end_pass
        # + sync load + full finalize.
        pass1_keys = int(ds.stats.keys)
        preload_join_s = 0.0
        t0 = time.perf_counter()
        if pipelined:
            ds.end_pass_async(trainer.trained_table_device())
            writeback_s = time.perf_counter() - t0  # dispatch only
            t0 = time.perf_counter()
            # load time not in boundary_s (r05 didn't count it either);
            # reported separately — near zero when the overlap worked
            ds.wait_preload_done()
            preload_join_s = time.perf_counter() - t0
        else:
            ds.end_pass(trainer.trained_table_device())
            writeback_s = time.perf_counter() - t0
            ds.set_filelist(files2)
            ds.load_into_memory()
        t0 = time.perf_counter()
        ds.begin_pass(round_to=512)
        finalize2_s = time.perf_counter() - t0
        pass2_keys = int(ds.ws.n_keys)
        # leave the 2nd pass clean: flush carried rows, close it out
        ds.end_pass(None)
        table.drain_pending()

    sps = timed_samples / train_s
    extra = {}
    if len(probe_log) > 1:
        # a recovered-after-retries chip is wedge evidence too — record the
        # failed probes even when the run ultimately lands on TPU
        extra["tpu_probe_log"] = probe_log
    if tpu_error is not None:
        extra["tpu_error"] = tpu_error
        extra["tpu_probe_log"] = probe_log
        loop_tail = read_probe_loop_tail()
        if loop_tail is not None:
            extra["tpu_probe_loop_tail"] = loop_tail
        last_good = read_last_good()
        if last_good is not None:
            if last_good.get("bench_config") == bench_config_id():
                extra["last_good_tpu"] = last_good
            else:
                extra["last_good_tpu_stale"] = {
                    "measured_at": last_good.get("measured_at"),
                    "bench_config": last_good.get("bench_config"),
                    "note": "cached TPU measurement predates a bench config "
                    "change; not comparable",
                }
        capture = read_last_capture()
        if capture is not None:
            # the probe-loop's full healthy-window capture: headline +
            # sweep + ablations + scatter decision, with its own
            # bench_config stamp for comparability
            extra["tpu_capture"] = capture
    if profile:
        # per-stage attribution (TrainFilesWithProfiler parity) — table to
        # stderr so stdout stays one JSON line for the driver
        prof = out.get("profile", {})
        extra["profile"] = prof
        print("stage breakdown (s):", file=sys.stderr)
        for k, v in prof.items():
            print(f"  {k:18s} {v:8.3f}", file=sys.stderr)
        for k, v in (("load", load_s), ("finalize", finalize_s), ("train", train_s)):
            print(f"  {k + '_total':18s} {v:8.3f}", file=sys.stderr)
    result = {
        **extra,
        "metric": (
            "deepfm_join_phase_samples_per_sec_per_chip"
            if pv
            else "deepfm_e2e_train_samples_per_sec_per_chip"
        ),
        "value": round(sps, 1),
        "unit": "samples/s/chip",
        "vs_baseline": round(sps / BASELINE_PER_CHIP, 3),
        "train_pass_s": round(train_s, 3),
        "load_s": round(load_s, 3),
        "finalize_s": round(finalize_s, 3),
        "writeback_s": round(writeback_s, 3),
        "finalize2_s": round(finalize2_s, 3),
        "boundary_s": round(writeback_s + finalize2_s, 3),
        "preload_join_s": round(preload_join_s, 3),
        "boundary_pipeline": int(pipelined),
        # per-stage boundary attribution (utils/monitor gauges set by the
        # feed stage, finalize, and the end_pass worker)
        "boundary_stages": {
            k: round(float(STAT_GET(f"boundary.{k}")), 4)
            for k in (
                "premerge_s", "prefetch_pull_s", "dedup_s", "pull_s",
                "splice_s", "writeback_s", "writeback_hidden_s",
                "overlap_hidden_s",
            )
        },
        # writer-pool writeback internals (table.writeback.* gauges from
        # PassWorkingSet.writeback + the native io counters published at
        # end_pass): pool size, chunk pipeline wait vs hidden seconds,
        # and the spill stage writers' gather/fwrite split
        "writeback_stages": {
            k: round(float(STAT_GET(f"table.writeback.{k}")), 4)
            for k in (
                "threads", "chunks", "push_s", "wait_s", "hidden_s",
                "spill_gather_s", "spill_fwrite_s", "prepass_read_s",
                "stage_flushes", "stage_bytes",
            )
        },
        # distribution view of the same stages (obs histograms): the
        # gauges above are last-pass values, these are across-the-run
        # count/mean/p50/p99 for every STAT_OBSERVE'd series
        "distributions": {
            name: hist.summary((0.5, 0.99))
            for name, hist in sorted(_all_histograms().items())
        },
        "warmup_s": round(warmup_s, 3),
        # backend bring-up verdict (utils/backendguard): "ok" or
        # "fallback_cpu" — the full probe_log rides in tpu_probe_log above
        "backend_init": {
            k: v for k, v in verdict.as_dict().items() if k != "probe_log"
        },
        # persistent-compile-cache counters: a cold run shows hits == 0,
        # the next identical run shows hits > 0 and a smaller warmup_s
        "compile_cache": compilecache.stats(),
        # bytes actually crossing the boundary wire this run (STAT
        # counters at the ops/wire_quant choke points) + the compiled ICI
        # a2a payload — the measured side of the wire_dtype claims
        "wire": {
            "wire_dtype": str(_config.get_flag("wire_dtype")),
            "fetch_rows": int(STAT_GET("wire.fetch_rows_total")),
            "fetch_bytes": int(STAT_GET("wire.fetch_bytes_total")),
            "fetch_fp32_bytes": int(STAT_GET("wire.fetch_fp32_bytes_total")),
            "send_rows": int(STAT_GET("wire.send_rows_total")),
            "send_bytes": int(STAT_GET("wire.send_bytes_total")),
            "send_fp32_bytes": int(STAT_GET("wire.send_fp32_bytes_total")),
            "ici_wire_dtype": str(_config.get_flag("ici_wire_dtype")),
            "a2a_payload_bytes": int(STAT_GET("wire.a2a_payload_bytes")),
            "a2a_fp32_bytes": int(STAT_GET("wire.a2a_fp32_bytes")),
            "a2a_dtype_bits": int(STAT_GET("wire.a2a_dtype_bits")),
            # adaptive ICI wire (hot rows bf16, cold tail int8): per-bucket
            # hot-slot bound the compiled collective used, plus the pass's
            # hotness census and how many hot keys overflowed into int8
            "a2a_hot_slots": int(STAT_GET("wire.a2a_hot_slots")),
            "ici_hot_keys": int(STAT_GET("wire.ici_hot_keys")),
            "ici_hot_overflow_keys": int(STAT_GET("wire.ici_hot_overflow_keys")),
            # host plane (PBTX v3 frame choke point + working-set
            # exchange rounds, ops/host_codec.py): actual bytes shipped
            # vs what the raw v2 framing would have shipped
            "host_wire_codec": bool(_config.get_flag("host_wire_codec")),
            "host_bytes_sent": int(STAT_GET("wire.host_bytes_sent")),
            "host_raw_bytes_sent": int(STAT_GET("wire.host_raw_bytes_sent")),
            "host_bytes_recv": int(STAT_GET("wire.host_bytes_recv")),
            "host_raw_bytes_recv": int(STAT_GET("wire.host_raw_bytes_recv")),
            "ws_req_bytes": int(STAT_GET("wire.ws_req_bytes")),
            "ws_req_raw_bytes": int(STAT_GET("wire.ws_req_raw_bytes")),
            "ws_rep_bytes": int(STAT_GET("wire.ws_rep_bytes")),
            "ws_rep_raw_bytes": int(STAT_GET("wire.ws_rep_raw_bytes")),
        },
        # which kernel plan routed pull/push this run, and how often it
        # chose pallas (ops/kernel_plan.py; regenerate with
        # tools/tune_kernels.py)
        "kernel_plan": {
            "source": _plan_source(),
            "selects": int(STAT_GET("kernel_plan.selects")),
            "selects_pallas": int(STAT_GET("kernel_plan.selects_pallas")),
        },
        # elastic membership (parallel/membership.py): ownership epoch,
        # fleet size and lifetime join commits — a single-process bench
        # leaves all three gauges at zero; the elastic soaks
        # (chaos_probe --kill-rank / --join-rank) move these
        "membership": {
            "epoch": int(STAT_GET("membership.epoch")),
            "live_ranks": int(STAT_GET("membership.live_ranks")),
            "joins_total": int(STAT_GET("membership.joins_total")),
        },
        # serving plane (serve/): miss ladder + device hot tier + the SLO
        # latency series — a pure-training bench leaves these at zero; the
        # serving soaks (tools/serve_soak.py [--device-tier]) move them
        "serve": {
            "key_misses": int(STAT_GET("serve.key_misses")),
            "device_tier_rows": int(STAT_GET("serve.device_tier_rows")),
            "device_tier_builds": int(STAT_GET("serve.device_tier_builds")),
            "device_tier_hits": int(STAT_GET("serve.device_tier_hits")),
            "device_tier_misses": int(STAT_GET("serve.device_tier_misses")),
            "device_tier_hit_rate": round(
                STAT_GET("serve.device_tier_hits")
                / max(
                    1.0,
                    STAT_GET("serve.device_tier_hits")
                    + STAT_GET("serve.device_tier_misses"),
                ),
                4,
            ),
            "lb_rerouted": int(STAT_GET("serve.lb_rerouted")),
            "request_ms": (
                _all_histograms()["serve.request_ms"].summary((0.5, 0.99))
                if "serve.request_ms" in _all_histograms()
                else None
            ),
        },
        # pass-prepare pad sweep (native pbx_block_stats counter sweep):
        # must stay a small fraction of train_pass_s at any pass size
        "prepare_s": round(getattr(trainer, "last_prepare_s", -1.0), 3),
        "pass2_keys": pass2_keys,
        "pass_keys": pass1_keys,
        "native_store": native_store,
        "platform": info["platform"],
        "auc": round(out["auc"], 4),
    }
    no_cache = os.environ.get("PBOX_BENCH_NO_CACHE", "0") == "1"
    if info["platform"] == "tpu" and not pv and not no_cache:
        # Cache this healthy-chip measurement; a later wedged run emits it
        # as "last_good_tpu" alongside its CPU fallback number. (pv-mode
        # runs live in the capture artifact's ablation slot instead, and
        # the capture tool sets PBOX_BENCH_NO_CACHE for its ablation/sweep
        # runs — bench_config_id doesn't encode knobs, so a degraded
        # non-default run must not shadow the default-knob headline.)
        try:
            cached = dict(result)
            cached["measured_at"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            )
            cached["bench_config"] = bench_config_id()
            with open(LAST_GOOD_PATH, "w") as f:
                json.dump(cached, f)
        except OSError:
            pass
    print(json.dumps(result))


if __name__ == "__main__":
    main()
