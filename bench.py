"""Headline benchmark: DeepFM CTR train-step throughput, samples/sec/chip.

Measures the steady-state jitted train step (sparse pull -> fused
seqpool+CVM -> DeepFM fwd/bwd -> sparse adagrad push -> dense adam -> online
AUC) on one chip with pre-packed static-shape batches — the device half of
the reference's BoxPSWorker::TrainFiles loop (boxps_worker.cc:420-466).

Baseline (BASELINE.json): 1M samples/sec on 64 chips => 15625 samples/sec/chip.
Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

# Criteo-DeepFM-ish flagship shape (BASELINE.md config 3)
NUM_SLOTS = 39
EMBEDX_DIM = 16
BATCH = 4096
TABLE_ROWS = 1 << 21  # ~2M pass working-set rows on chip
HIDDEN = (512, 256, 128)
WARMUP = 5
STEPS = 40
BASELINE_PER_CHIP = 1_000_000 / 64


def make_batches(rng, n_batches, rows_limit, bucket=512):
    """Pre-packed DeviceBatch dicts with ONE static shape across batches."""
    L = NUM_SLOTS * BATCH  # one key per slot per sample
    batches = []
    u_pad = None
    raw = []
    for _ in range(n_batches):
        # zipf-ish skew: mix hot head with uniform tail, like CTR traffic
        hot = rng.integers(0, 1 << 12, L // 4)
        cold = rng.integers(0, rows_limit - 1, L - L // 4)
        rows = np.concatenate([hot, cold]).astype(np.int64)
        rng.shuffle(rows)
        uniq, inverse = np.unique(rows, return_inverse=True)
        raw.append((uniq, inverse))
        need = -(-(len(uniq) + 1) // bucket) * bucket
        u_pad = max(u_pad or 0, need)
    for uniq, inverse in raw:
        uniq_p = np.full(u_pad, rows_limit - 1, np.int32)  # pad -> padding row
        uniq_p[: len(uniq)] = uniq
        inv = inverse.astype(np.int32)  # L is exact here, no key padding needed
        seg = np.repeat(np.arange(NUM_SLOTS, dtype=np.int32), BATCH) * BATCH + np.tile(
            np.arange(BATCH, dtype=np.int32), NUM_SLOTS
        )
        labels = (rng.random(BATCH) < 0.2).astype(np.float32)
        batches.append(
            {
                "uniq_rows": uniq_p,
                "inverse": inv,
                "segments": seg,
                "labels": labels,
            }
        )
    return batches


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.table import SparseOptimizerConfig, ValueLayout
    from paddlebox_tpu.train import TrainStepConfig, make_train_step
    from paddlebox_tpu.train.train_step import init_train_state, jit_train_step

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    layout = ValueLayout(embedx_dim=EMBEDX_DIM)
    opt_cfg = SparseOptimizerConfig(embedx_threshold=0.0)

    table = np.zeros((TABLE_ROWS, layout.width), np.float32)
    table[:, layout.embed_w_col] = rng.normal(0, 1e-2, TABLE_ROWS)
    table[:, layout.embedx_col : layout.embedx_col + EMBEDX_DIM] = rng.normal(
        0, 1e-2, (TABLE_ROWS, EMBEDX_DIM)
    )
    table[TABLE_ROWS - 1] = 0.0  # padding row

    model = DeepFM(
        num_slots=NUM_SLOTS, feat_width=layout.pull_width, embedx_dim=EMBEDX_DIM, hidden=HIDDEN
    )
    params = model.init(jax.random.PRNGKey(0))
    dense_opt = optax.adam(1e-3)
    cfg = TrainStepConfig(
        num_slots=NUM_SLOTS,
        batch_size=BATCH,
        layout=layout,
        sparse_opt=opt_cfg,
        auc_buckets=100_000,
    )
    step = jit_train_step(make_train_step(model.apply, dense_opt, cfg))
    state = init_train_state(
        jax.device_put(jnp.asarray(table), dev), params, dense_opt, cfg.auc_buckets
    )

    host_batches = make_batches(rng, 8, TABLE_ROWS)
    feeds = [
        {k: jax.device_put(jnp.asarray(v), dev) for k, v in b.items()} for b in host_batches
    ]

    for i in range(WARMUP):
        state, m = step(state, feeds[i % len(feeds)])
    jax.block_until_ready(state.table)

    t0 = time.perf_counter()
    for i in range(STEPS):
        state, m = step(state, feeds[i % len(feeds)])
    jax.block_until_ready(state.table)
    dt = time.perf_counter() - t0

    sps = STEPS * BATCH / dt
    print(
        json.dumps(
            {
                "metric": "deepfm_train_samples_per_sec_per_chip",
                "value": round(sps, 1),
                "unit": "samples/s/chip",
                "vs_baseline": round(sps / BASELINE_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
